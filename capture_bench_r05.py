"""One-shot round-5 bench capture (run the MOMENT the tunnel is back):

    python capture_bench_r05.py

Runs the 5 BASELINE configs plus the three opt-in configs
(transformer_scan, transformer_fused, moe_transformer) SEQUENTIALLY in
separate processes (one TPU claim at a time, per the tunnel rules) and
writes every JSON line to BENCH_SELF_r05.json. Each sub-run inherits
bench.py's fail-fast probe, so a dead tunnel costs 180 s, not a hang.

The transformer vs transformer_fused pair is the whole-layer-fusion
A/B PERF.md describes — record BOTH numbers.
"""
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "BENCH_SELF_r05.json")


def run(args):
    """One bench.py sub-run. A hung config (the exact hang-prone-
    tunnel scenario this one-shot script exists for) must not abort
    the capture: TimeoutExpired is recorded as rc='timeout' and the
    next config still runs (ADVICE r5)."""
    print(f"# capture: python bench.py {' '.join(args)}",
          file=sys.stderr, flush=True)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "bench.py"), *args],
            capture_output=True, text=True, timeout=3600)
    except subprocess.TimeoutExpired as e:
        print(f"# capture: TIMEOUT after {e.timeout}s for config "
              f"{args or ['default']}; recording marker and moving on",
              file=sys.stderr, flush=True)
        return "timeout", []
    sys.stderr.write(proc.stderr)
    lines = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            lines.append(json.loads(line))
    return proc.returncode, lines


def main():
    results = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
               "runs": []}

    def flush():
        # partial results are the whole point: write after EVERY
        # config so a later hang/kill loses nothing
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)

    try:
        rc, lines = run([])  # the 5 BASELINE configs
        results["default_rc"] = rc
        results["runs"] += lines
        flush()
        if rc == 3:
            print("# capture: backend dead (rc=3); wrote probe record",
                  file=sys.stderr)
            return 3
        for extra in ("transformer_scan", "transformer_fused",
                      "transformer_scan_fused", "moe_transformer"):
            rc_e, lines_e = run([extra])
            results["runs"] += lines_e
            results[f"{extra}_rc"] = rc_e
            flush()
    finally:
        flush()
    print(f"# capture: wrote {OUT} with {len(results['runs'])} "
          f"result lines", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
