"""One-shot round-5 bench capture (run the MOMENT the tunnel is back):

    python capture_bench_r05.py

Runs the 5 BASELINE configs plus the three opt-in configs
(transformer_scan, transformer_fused, moe_transformer) SEQUENTIALLY in
separate processes (one TPU claim at a time, per the tunnel rules) and
writes every JSON line to BENCH_SELF_r05.json. Each sub-run inherits
bench.py's fail-fast probe, so a dead tunnel costs 180 s, not a hang.

The transformer vs transformer_fused pair is the whole-layer-fusion
A/B PERF.md describes — record BOTH numbers.
"""
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "BENCH_SELF_r05.json")


def run(args):
    print(f"# capture: python bench.py {' '.join(args)}",
          file=sys.stderr, flush=True)
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, "bench.py"), *args],
        capture_output=True, text=True, timeout=3600)
    sys.stderr.write(proc.stderr)
    lines = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            lines.append(json.loads(line))
    return proc.returncode, lines


def main():
    results = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
               "runs": []}
    rc, lines = run([])  # the 5 BASELINE configs
    results["default_rc"] = rc
    results["runs"] += lines
    if rc == 3:
        print("# capture: backend dead (rc=3); writing probe record",
              file=sys.stderr)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)
        return 3
    for extra in ("transformer_scan", "transformer_fused",
                  "transformer_scan_fused", "moe_transformer"):
        rc_e, lines_e = run([extra])
        results["runs"] += lines_e
        results[f"{extra}_rc"] = rc_e
    with open(OUT, "w") as f:
        json.dump(results, f, indent=1)
    print(f"# capture: wrote {OUT} with {len(results['runs'])} "
          f"result lines", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
