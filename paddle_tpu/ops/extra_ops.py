"""Round-2 op-gap closure: pooling/conv3d extensions, structural
losses, misc math, in-graph save/load + print/is_empty utilities.

Parity targets (reference paddle/fluid/operators/): pool_op.cc (pool3d),
pool_with_index_op.cc, conv_transpose_op.cc (conv3d_transpose),
spp_op.h, unpool_op.h, bilinear_tensor_product_op.h, rank_loss_op.h,
modified_huber_loss_op.h, squared_l2_distance_op.h,
teacher_student_sigmoid_loss_op.h, conv_shift_op.cc,
add_position_encoding_op.h, data_norm_op.cc, random_crop_op.h,
is_empty_op.cc, print_op.cc, save_op.cc, load_op.cc,
save_combine_op.cc, load_combine_op.cc,
get_tensor_from_selected_rows_op.cc, merge_selected_rows_op.cc.

All are fresh XLA-idiom implementations: windowed reductions via
lax.reduce_window, argmax pooling via an im2col gather (static shapes,
MXU/VPU friendly), circular convolution via jnp.roll-free modular
gather, in-graph checkpoint IO via ordered io_callback (the reference
runs save/load as graph ops inside the executor; the callback is the
jit-compatible form of the same contract).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

__all__ = []


def _triple(v):
    if isinstance(v, (list, tuple)):
        return list(v) if len(v) == 3 else [v[0]] * 3
    return [v] * 3


def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v) if len(v) == 2 else [v[0]] * 2
    return [v] * 2


# --------------------------------------------------------------------------
# pooling family
# --------------------------------------------------------------------------
@register_op("pool3d")
def pool3d(ctx):
    """reference pool_op.cc (pool3d kernel): NCDHW max/avg pooling."""
    x = ctx.input("X")
    ptype = ctx.attr("pooling_type", "max")
    ksize = _triple(ctx.attr("ksize", [2, 2, 2]))
    strides = _triple(ctx.attr("strides", [1, 1, 1]))
    pads = _triple(ctx.attr("paddings", [0, 0, 0]))
    if ctx.attr("global_pooling", False):
        ksize = list(x.shape[2:5])
        pads = [0, 0, 0]
        strides = [1, 1, 1]
    window = (1, 1) + tuple(ksize)
    strides_ = (1, 1) + tuple(strides)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pads)
    if ptype == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, window, strides_,
                                 padding)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides_, padding)
    if ctx.attr("exclusive", True) and any(pads):
        cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                strides_, padding)
        return s / cnt
    return s / float(np.prod(ksize))


def _pool_with_index(x, ksize, strides, pads, spatial):
    """im2col argmax pooling returning (values, flat-input indices).

    The reference mask is the position within the flattened spatial
    input (pool_with_index_op.h). Static-shape gather keeps XLA happy;
    out-of-window (padding) cells are masked to -inf so they never win.
    """
    n, c = x.shape[:2]
    in_sp = x.shape[2:]
    out_sp = [(in_sp[d] + 2 * pads[d] - ksize[d]) // strides[d] + 1
              for d in range(spatial)]
    # per-output-cell absolute input coordinates, one axis at a time
    coords = []
    valid = None
    for d in range(spatial):
        o = jnp.arange(out_sp[d]) * strides[d] - pads[d]
        k = jnp.arange(ksize[d])
        cd = o[:, None] + k[None, :]  # [out_d, k_d]
        ok = (cd >= 0) & (cd < in_sp[d])
        coords.append((jnp.clip(cd, 0, in_sp[d] - 1), ok))
        valid = ok if valid is None else valid
    if spatial == 2:
        (ch, okh), (cw, okw) = coords
        # windows [OH, OW, kh, kw]
        hh = ch[:, None, :, None]
        ww = cw[None, :, None, :]
        ok = okh[:, None, :, None] & okw[None, :, None, :]
        flat_idx = hh * in_sp[1] + ww
        patches = x[:, :, hh, ww]  # [N, C, OH, OW, kh, kw]
        patches = jnp.where(ok[None, None], patches, -jnp.inf)
        pf = patches.reshape(n, c, out_sp[0], out_sp[1], -1)
        arg = jnp.argmax(pf, axis=-1)
        out = jnp.max(pf, axis=-1)
        fi = flat_idx.reshape(out_sp[0], out_sp[1], -1)
        mask = jnp.take_along_axis(
            jnp.broadcast_to(fi[None, None], pf.shape[:-1] + fi.shape[-1:]),
            arg[..., None], axis=-1)[..., 0]
        return out, mask.astype(jnp.int32)
    # spatial == 3
    (cd_, okd), (ch, okh), (cw, okw) = coords
    dd = cd_[:, None, None, :, None, None]
    hh = ch[None, :, None, None, :, None]
    ww = cw[None, None, :, None, None, :]
    ok = (okd[:, None, None, :, None, None]
          & okh[None, :, None, None, :, None]
          & okw[None, None, :, None, None, :])
    flat_idx = (dd * in_sp[1] + hh) * in_sp[2] + ww
    patches = x[:, :, dd, hh, ww]
    patches = jnp.where(ok[None, None], patches, -jnp.inf)
    pf = patches.reshape(n, c, out_sp[0], out_sp[1], out_sp[2], -1)
    arg = jnp.argmax(pf, axis=-1)
    out = jnp.max(pf, axis=-1)
    fi = flat_idx.reshape(out_sp[0], out_sp[1], out_sp[2], -1)
    mask = jnp.take_along_axis(
        jnp.broadcast_to(fi[None, None], pf.shape[:-1] + fi.shape[-1:]),
        arg[..., None], axis=-1)[..., 0]
    return out, mask.astype(jnp.int32)


@register_op("max_pool2d_with_index", stop_gradient_slots=())
def max_pool2d_with_index(ctx):
    """reference pool_with_index_op.cc: Out + Mask of flat h*w index."""
    x = ctx.input("X")
    ksize = _pair(ctx.attr("ksize", [2, 2]))
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    if ctx.attr("global_pooling", False):
        ksize = list(x.shape[2:4])
        pads = [0, 0]
    out, mask = _pool_with_index(x, ksize, strides, pads, 2)
    return {"Out": out, "Mask": mask}


@register_op("max_pool3d_with_index", stop_gradient_slots=())
def max_pool3d_with_index(ctx):
    x = ctx.input("X")
    ksize = _triple(ctx.attr("ksize", [2, 2, 2]))
    strides = _triple(ctx.attr("strides", [1, 1, 1]))
    pads = _triple(ctx.attr("paddings", [0, 0, 0]))
    if ctx.attr("global_pooling", False):
        ksize = list(x.shape[2:5])
        pads = [0, 0, 0]
    out, mask = _pool_with_index(x, ksize, strides, pads, 3)
    return {"Out": out, "Mask": mask}


@register_op("unpool")
def unpool(ctx):
    """reference unpool_op.h (unpooling_type='max'): scatter pooled
    values back to the positions recorded in Indices."""
    x = ctx.input("X")
    idx = ctx.input("Indices")
    ksize = _pair(ctx.attr("ksize", [2, 2]))
    strides = _pair(ctx.attr("strides", [2, 2]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    n, c, h, w = x.shape
    oh = (h - 1) * strides[0] - 2 * pads[0] + ksize[0]
    ow = (w - 1) * strides[1] - 2 * pads[1] + ksize[1]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    out = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1)].add(x.reshape(n, c, -1))
    return out.reshape(n, c, oh, ow)


@register_op("spp")
def spp(ctx):
    """reference spp_op.h: pyramid of 2^p-bin poolings, flattened and
    concatenated along channels."""
    x = ctx.input("X")
    height = ctx.attr("pyramid_height", 1)
    ptype = ctx.attr("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for p in range(height):
        bins = 2 ** p
        kh = -(-h // bins)  # ceil
        kw = -(-w // bins)
        ph = (kh * bins - h + 1) // 2
        pw = (kw * bins - w + 1) // 2
        window = (1, 1, kh, kw)
        strides = (1, 1, kh, kw)
        padding = ((0, 0), (0, 0), (ph, ph), (pw, pw))
        if ptype == "max":
            o = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                  padding)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, window, strides,
                                  padding)
            cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                    window, strides, padding)
            o = s / cnt
        outs.append(o[:, :, :bins, :bins].reshape(n, -1))
    return jnp.concatenate(outs, axis=1)


@register_op("conv3d_transpose")
def conv3d_transpose(ctx):
    """reference conv_transpose_op.cc conv3d_transpose: NCDHW."""
    x = ctx.input("Input")
    w = ctx.input("Filter")  # [in_c, out_c/groups, kd, kh, kw]
    strides = _triple(ctx.attr("strides", [1, 1, 1]))
    pads = _triple(ctx.attr("paddings", [0, 0, 0]))
    dilations = _triple(ctx.attr("dilations", [1, 1, 1]))
    groups = ctx.attr("groups", 1)
    from .nn_ops import _conv_transpose_nd

    return {"Output": _conv_transpose_nd(x, w, strides, pads,
                                         dilations, groups, spatial=3)}


# --------------------------------------------------------------------------
# structural losses / math
# --------------------------------------------------------------------------
@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ctx):
    """reference bilinear_tensor_product_op.h: out[b,k] =
    x[b] @ W[k] @ y[b] + bias[k]."""
    x, y = ctx.input("X"), ctx.input("Y")
    w = ctx.input("Weight")  # [K, dx, dy]
    bias = ctx.input("Bias")
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if bias is not None:
        out = out + bias.reshape(1, -1)
    return out


@register_op("rank_loss", stop_gradient_slots=("Label",))
def rank_loss(ctx):
    """reference rank_loss_op.h:40: log(1+exp(o)) - label*o,
    o = left - right (RankNet)."""
    label = ctx.input("Label")
    o = ctx.input("Left") - ctx.input("Right")
    return jnp.logaddexp(0.0, o) - label * o


@register_op("modified_huber_loss", stop_gradient_slots=("Y",))
def modified_huber_loss(ctx):
    """reference modified_huber_loss_op.h: z = x*(2y-1);
    loss = -4z if z<-1; (1-z)^2 if -1<=z<1; 0 otherwise."""
    x = ctx.input("X")
    y = ctx.input("Y")
    z = x * (2.0 * y - 1.0)
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, (1.0 - z) ** 2, 0.0))
    return {"IntermediateVal": z, "Out": loss}


@register_op("squared_l2_distance")
def squared_l2_distance(ctx):
    """reference squared_l2_distance_op.h: row-wise ||x-y||^2 (y may be
    a single row broadcast over the batch)."""
    x, y = ctx.input("X"), ctx.input("Y")
    sub = x - y
    return {"sub_result": sub,
            "Out": jnp.sum(sub * sub, axis=tuple(range(1, sub.ndim)),
                           keepdims=False).reshape(-1, 1)}


@register_op("teacher_student_sigmoid_loss",
             stop_gradient_slots=("Label",))
def teacher_student_sigmoid_loss(ctx):
    """reference teacher_student_sigmoid_loss_op.h:34-63; label encodes
    (teacher score z', click z): <-1 no-teacher/no-click, [-1,0)
    no-teacher/click, [0,1) teacher+no-click, >=1 teacher+click."""
    x = ctx.input("X").reshape(-1)
    label = ctx.input("Label").reshape(-1).astype(x.dtype)
    sp = jnp.maximum(x, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(x)))
    no_t_no_c = sp
    no_t_c = sp - x
    t_no_c = sp + sp - x * label
    t_c = sp - x + sp - x * (label - 1.0)
    y = jnp.where(label < -1.0, no_t_no_c,
                  jnp.where(label < 0.0, no_t_c,
                            jnp.where(label < 1.0, t_no_c, t_c)))
    return y.reshape(-1, 1)


@register_op("conv_shift")
def conv_shift(ctx):
    """reference conv_shift_op.cc:127-132 circular convolution:
    out[b,i] = sum_j x[b, (i + j - w/2) mod n] * y[b,j]."""
    x, y = ctx.input("X"), ctx.input("Y")
    n = x.shape[1]
    w = y.shape[1]
    half = w // 2
    # static numpy index grid: x may be a concrete array under the
    # fd-grad harness while the cotangent is traced
    i = np.arange(n)[:, None]
    j = np.arange(w)[None, :]
    idx = (i + j - half) % n  # [n, w]
    return jnp.einsum("bnw,bw->bn", jnp.asarray(x)[:, idx], y)


@register_op("add_position_encoding")
def add_position_encoding(ctx):
    """reference add_position_encoding_op.h:55-80: out = alpha*x +
    beta*PE with sin on the first half of channels, cos on the second;
    frequency j / 10000^(k/(half-1))."""
    x = ctx.input("X")  # [B, T, D]
    alpha = ctx.attr("alpha", 1.0)
    beta = ctx.attr("beta", 1.0)
    b, t, d = x.shape
    half = d // 2
    j = jnp.arange(t, dtype=jnp.float32)[:, None]
    k = jnp.arange(half, dtype=jnp.float32)[None, :]
    denom = jnp.power(10000.0, k / max(half - 1, 1))
    val = j / denom  # [T, half]
    pe = jnp.concatenate([jnp.sin(val), jnp.cos(val)], axis=-1)
    return alpha * x + beta * pe[None].astype(x.dtype)


@register_op("data_norm")
def data_norm(ctx):
    """reference data_norm_op.cc:190-200: means = batch_sum/batch_size,
    scales = sqrt(batch_size/batch_square_sum), y = (x-means)*scales.
    The three accumulators are updated in place with this batch's
    sums (the reference routes the update through its grad op; the
    in-place form is the single-program equivalent)."""
    x = ctx.input("X")  # [N, C]
    bsize = ctx.input("BatchSize")        # [C]
    bsum = ctx.input("BatchSum")          # [C]
    bsq = ctx.input("BatchSquareSum")     # [C]
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsq)
    y = (x - means.reshape(1, -1)) * scales.reshape(1, -1)
    n = x.shape[0]
    out = {"Y": y, "Means": means, "Scales": scales}
    if ctx.op.outputs.get("BatchSizeOut"):
        out["BatchSizeOut"] = bsize + n
        out["BatchSumOut"] = bsum + x.sum(0)
        out["BatchSquareSumOut"] = bsq + (x * x).sum(0)
    return out


@register_op("random_crop", differentiable=False, needs_rng=True)
def random_crop(ctx):
    """reference random_crop_op.h: per-instance random crop of the
    trailing dims to attr shape."""
    x = ctx.input("X")
    shape = list(ctx.attr("shape"))
    k = len(shape)
    batch_dims = x.shape[:x.ndim - k]
    nb = int(np.prod(batch_dims)) if batch_dims else 1
    xf = x.reshape((nb,) + x.shape[x.ndim - k:])
    keys = jax.random.split(ctx.rng(), nb * k).reshape(nb, k, 2)

    def one(inst, ks):
        slices = []
        starts = [jax.random.randint(ks[d], (), 0,
                                     inst.shape[d] - shape[d] + 1)
                  for d in range(k)]
        return lax.dynamic_slice(inst, starts, shape)

    out = jax.vmap(one)(xf, keys)
    return out.reshape(batch_dims + tuple(shape))


# --------------------------------------------------------------------------
# utility / io ops
# --------------------------------------------------------------------------
@register_op("is_empty", differentiable=False)
def is_empty(ctx):
    """reference is_empty_op.cc: scalar bool numel == 0 (static under
    XLA, so a compile-time constant)."""
    return jnp.asarray(ctx.input("X").size == 0)


@register_op("print", differentiable=False, host_effect=True)
def print_op(ctx):
    """reference print_op.cc: pass-through + host-side print via
    ordered io_callback (message/first_n/summarize attrs honored)."""
    from jax.experimental import io_callback

    x = ctx.input("X")
    message = ctx.attr("message", "")
    first_n = ctx.attr("first_n", -1)
    summarize = ctx.attr("summarize", -1)
    counter = [0]

    def _emit(val):
        counter[0] += 1
        if first_n < 0 or counter[0] <= first_n:
            flat = np.asarray(val).reshape(-1)
            if summarize and summarize > 0:
                flat = flat[:summarize]
            print(f"{message} {np.asarray(val).shape} {flat}")
        return np.zeros((), np.int32)

    io_callback(_emit, jax.ShapeDtypeStruct((), jnp.int32), x,
                ordered=True)
    return {"Out": x}


@register_op("save", differentiable=False, host_effect=True)
def save_op(ctx):
    """reference save_op.cc: persist one variable to file_path from
    inside the graph (ordered io_callback keeps step ordering)."""
    from jax.experimental import io_callback

    x = ctx.input("X")
    path = ctx.attr("file_path")
    overwrite = ctx.attr("overwrite", True)

    def _save(val):
        import os

        if not overwrite and os.path.exists(path):
            raise RuntimeError(f"{path} exists and overwrite=False")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.save(path, np.asarray(val), allow_pickle=False)
        return np.zeros((), np.int32)

    io_callback(_save, jax.ShapeDtypeStruct((), jnp.int32), x,
                ordered=True)
    return None


@register_op("load", differentiable=False, host_effect=True)
def load_op(ctx):
    """reference load_op.cc. XLA needs static result shapes, so the
    layer records the target var's shape/dtype as attrs at build time
    (io.py wires them); the value itself is read at execution."""
    from jax.experimental import io_callback

    path = ctx.attr("file_path")
    shape = tuple(ctx.attr("shape"))
    dtype = jnp.dtype(ctx.attr("dtype", "float32"))

    def _load():
        arr = np.load(path if path.endswith(".npy") else path + ".npy")
        return np.ascontiguousarray(arr.astype(dtype)).reshape(shape)

    return io_callback(_load, jax.ShapeDtypeStruct(shape, dtype),
                       ordered=True)


@register_op("save_combine", differentiable=False, host_effect=True)
def save_combine(ctx):
    """reference save_combine_op.cc: many vars -> ONE file (npz keyed
    by input var name)."""
    from jax.experimental import io_callback

    xs = ctx.inputs("X")
    names = list(ctx.op.inputs["X"])
    path = ctx.attr("file_path")

    def _save(*vals):
        import os

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path, **{n: np.asarray(v)
                          for n, v in zip(names, vals)})
        return np.zeros((), np.int32)

    io_callback(_save, jax.ShapeDtypeStruct((), jnp.int32), *xs,
                ordered=True)
    return None


@register_op("load_combine", differentiable=False, host_effect=True)
def load_combine(ctx):
    """reference load_combine_op.cc: restore N vars from one file; the
    layer supplies shapes/dtypes attrs for static results."""
    from jax.experimental import io_callback

    path = ctx.attr("file_path")
    # npz keys: the names the vars were SAVED under (attr), falling
    # back to this op's output var names when they match
    names = list(ctx.attr("names") or ctx.op.outputs["Out"])
    shapes = [tuple(s) for s in ctx.attr("shapes")]
    dtypes = [jnp.dtype(d) for d in ctx.attr("dtypes")]

    def _load():
        p = path if path.endswith(".npz") else path + ".npz"
        z = np.load(p)
        return tuple(
            np.ascontiguousarray(z[n].astype(dt)).reshape(sh)
            for n, sh, dt in zip(names, shapes, dtypes))

    specs = tuple(jax.ShapeDtypeStruct(sh, dt)
                  for sh, dt in zip(shapes, dtypes))
    vals = io_callback(_load, specs, ordered=True)
    return {"Out": list(vals)}


# --------------------------------------------------------------------------
# SelectedRows bridges. Sparse rows are modeled as a (rows, values)
# pair of dense tensors (rows int64 ids, values the per-row data) --
# the static-shape encoding of reference selected_rows.h.
# --------------------------------------------------------------------------
@register_op("merge_selected_rows", differentiable=False)
def merge_selected_rows(ctx):
    """reference merge_selected_rows_op.cc: sum duplicate row ids.
    Static-shape form: rows keep their slots; values of duplicate ids
    are summed into the FIRST occurrence, later duplicates zeroed and
    their row id set to -1 (padding)."""
    rows = ctx.input("Rows")
    vals = ctx.input("Values")
    n = rows.shape[0]
    eq = rows[None, :] == rows[:, None]          # [n, n]
    first = jnp.argmax(eq, axis=1)               # first occurrence idx
    is_first = first == jnp.arange(n)
    # scatter-add every row's values into its first occurrence
    merged = jnp.zeros_like(vals).at[first].add(vals)
    merged = jnp.where(is_first[:, None], merged, 0)
    out_rows = jnp.where(is_first, rows, -1)
    return {"OutRows": out_rows, "OutValues": merged}


@register_op("get_tensor_from_selected_rows", differentiable=False)
def get_tensor_from_selected_rows(ctx):
    """reference get_tensor_from_selected_rows_op.cc: densify a
    (rows, values) pair into [height, width] (height attr; padding
    rows id<0 are dropped)."""
    rows = ctx.input("Rows")
    vals = ctx.input("Values")
    height = ctx.attr("height")
    safe = jnp.where(rows >= 0, rows, height)  # dropped via mode=drop
    dense = jnp.zeros((height,) + vals.shape[1:], vals.dtype)
    return dense.at[safe].add(vals, mode="drop")
