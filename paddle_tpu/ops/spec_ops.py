"""Sampling-lane + speculative-decode kernels (models/decode_engine.py).

Reference counterpart: none — the reference framework's decode surface
is greedy/beam only (reference tests/unittests/dist_transformer.py:1498
fast_decode). The temperature/top-k/top-p lanes follow the standard
serving samplers; the draft-and-verify acceptance follows Leviathan et
al.'s speculative sampling (the vLLM spec-decode worker's rejection
rule), re-designed for XLA static shapes: the whole accept/advance
decision is ONE pure kernel over [R, k(+1), V] stacks so the hairy
per-lane math lives in one numpy-oracle-testable surface instead of a
fifty-op layer composition.

Noise discipline (deliberate deviation from the `(step key, op._uid)`
chain the training-time sampling ops use, CLAUDE.md invariant): serving
emission noise must be invariant to WHICH serve specialization
processes a position — admission order, burst boundaries, and paged
recompute-preemption all change the dispatch sequence, and byte-exact
re-decode of a preempted lane requires the noise at (request, position)
to be a pure function of those two. So every draw here derives from
``fold_in`` chains over (base_seed attr, noise_tag attr, per-lane Seed,
per-lane Pos) — never the advancing executor step key and never the
op's uid (the same logical draw appears in MANY programs of one serve
bundle, each with different uids). The ops still register
``needs_rng=True`` so the PTA030 uid sweep covers them; tag separation
(draft/accept/residual/bonus draws use distinct tags) is the builder's
half of the non-collision contract.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op

# draw-purpose tags folded into the key chain on TOP of the builder's
# noise_tag: the same (seed, pos) must give INDEPENDENT draws for the
# draft proposal, the acceptance uniform, and the residual/bonus sample
_TAG_ACCEPT = 101
_TAG_RESID = 102


def _base_key(base_seed: int, tag: int):
    return jax.random.fold_in(
        jax.random.PRNGKey(int(base_seed) & 0x7FFFFFFF), int(tag))


def _lane_keys(base, seed, pos):
    """[R] (or [R, J]) PRNG keys: fold_in(fold_in(base, seed), pos),
    vmapped over lanes (and positions). Pure in (seed, pos) — the
    admission-order / burst-length / preemption-replay invariance the
    serving layer's byte-exact contracts rest on."""
    seed = seed.astype(jnp.uint32)
    pos = pos.astype(jnp.uint32)

    def kf(s, p):
        return jax.random.fold_in(jax.random.fold_in(base, s), p)

    if pos.ndim == 2:
        return jax.vmap(jax.vmap(kf, in_axes=(None, 0)))(seed, pos)
    return jax.vmap(kf)(seed, pos)


def _filter_probs(logits, temperature, top_k, top_p):
    """Temperature/top-k/top-p filtered, renormalized probabilities
    over the last axis. temperature == 0 is the greedy degenerate
    case: a one-hot at argmax, which makes greedy acceptance an exact
    special case of the rejection rule (spec_accept docstring)."""
    v = logits.shape[-1]
    if temperature == 0.0:
        return jax.nn.one_hot(jnp.argmax(logits, axis=-1), v,
                              dtype=jnp.float32)
    z = (logits / float(temperature)).astype(jnp.float32)
    if top_k and 0 < int(top_k) < v:
        kth = jax.lax.top_k(z, int(top_k))[0][..., -1:]
        z = jnp.where(z >= kth, z, -jnp.inf)
    p = jax.nn.softmax(z, axis=-1)
    if top_p and float(top_p) < 1.0:
        ps = jnp.sort(p, axis=-1)[..., ::-1]
        cs = jnp.cumsum(ps, axis=-1)
        # nucleus: smallest set whose mass reaches top_p (the top-1
        # token always survives: its exclusive cumsum is 0 < top_p)
        keep_sorted = (cs - ps) < float(top_p)
        cutoff = jnp.min(jnp.where(keep_sorted, ps, jnp.inf),
                         axis=-1, keepdims=True)
        p = jnp.where(p >= cutoff, p, 0.0)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
    return p


@register_op("filtered_softmax", differentiable=False,
             stop_gradient_slots=("X",))
def filtered_softmax(ctx):
    """[..., V] logits -> temperature/top-k/top-p filtered normalized
    probabilities (temperature 0 -> one-hot argmax). attrs:
    temperature, top_k, top_p."""
    return _filter_probs(ctx.input("X"),
                         float(ctx.attr("temperature", 1.0)),
                         int(ctx.attr("top_k", 0) or 0),
                         float(ctx.attr("top_p", 1.0)))


@register_op("sample_categorical", differentiable=False, needs_rng=True,
             stop_gradient_slots=("Probs", "Seed", "Pos"))
def sample_categorical(ctx):
    """One token per lane from [R, V] probabilities, keyed purely by
    (base_seed, noise_tag, Seed[r], Pos[r]) — see the module docstring
    for why the executor step key deliberately stays out."""
    probs = ctx.input("Probs")
    seed = ctx.input("Seed").reshape(-1)
    pos = ctx.input("Pos").reshape(-1)
    base = _base_key(ctx.attr("base_seed", 0), ctx.attr("noise_tag", 0))
    keys = _lane_keys(base, seed, pos)
    logp = jnp.log(probs.astype(jnp.float32) + 1e-20)
    tok = jax.vmap(jax.random.categorical)(keys, logp)
    return {"Out": tok.astype(jnp.int64)}


@register_op("span_scatter", differentiable=False,
             stop_gradient_slots=("X", "Vals", "Start", "Count"))
def span_scatter(ctx):
    """Write Vals[r, :Count[r]] into Buf[r, Start[r]:Start[r]+Count[r]]
    per row (in place: Out is the Buf var — the accepted-prefix token
    write of the speculative step). Rows are disjoint by construction
    (per-lane buffers), so no pool-exclusivity contract applies."""
    buf = ctx.input("X")
    vals = ctx.input("Vals")
    start = ctx.input("Start").reshape(-1)
    count = ctx.input("Count").reshape(-1)
    t = buf.shape[1]
    w = vals.shape[1]
    pos = jnp.arange(t)[None, :]
    rel = pos - start[:, None]
    sel = (rel >= 0) & (rel < count[:, None]) & (rel < w)
    relc = jnp.clip(rel, 0, w - 1)
    vals_at = jnp.take_along_axis(vals, relc, axis=1)
    return jnp.where(sel, vals_at.astype(buf.dtype), buf)


@register_op("spec_accept", differentiable=False, needs_rng=True,
             stop_gradient_slots=("Proposals", "DraftProbs",
                                  "TargetProbs", "Seed", "Pos"))
def spec_accept(ctx):
    """Draft-and-verify acceptance (Leviathan et al. speculative
    sampling) for one batched lane step.

    inputs (R lanes, k proposals, vocab V):
      Proposals   [R, k]      draft tokens for positions Pos+1..Pos+k
      DraftProbs  [R, k, V]   filtered draft dists those tokens were
                              drawn from (one-hot under greedy)
      TargetProbs [R, k+1, V] filtered target dists for positions
                              Pos+1..Pos+k+1 (the verify step's k+1
                              query outputs)
      Seed, Pos   [R]         noise seed / current step counter
    attrs: k, end_id, max_len, greedy, base_seed, noise_tag.

    Per lane: accept proposal j while u_j * q_j(x_j) < p_j(x_j)
    (u_j ~ U[0,1) keyed on (seed, pos+1+j) — strict `<` makes the
    greedy one-hot case exactly deterministic: a match always accepts,
    a mismatch never does, regardless of u). At the first rejection
    sample the correction from norm(max(p - q, 0)); with all k
    accepted, sample the bonus token from p_k. Under attr greedy the
    correction/bonus is argmax instead of a draw, so greedy
    speculative decoding is TOKEN-EXACT vs the whole-loop greedy
    decode (the r10 parity contract).

    The emitted run is then clipped at the first end_id (the lane
    finishes THERE, matching the whole-loop EOS freeze) and at the
    buffer room max_len-1 - Pos. outputs:
      Advance  [R] emitted token count this step (0..k+1, and <= room)
      Tokens   [R, k+1] emitted tokens (first Advance entries valid)
      Accepted [R] how many emitted tokens were accepted draft
                   proposals (the acceptance-rate numerator)
      Fin      [R] 1 iff the advance ends with end_id
    """
    props = ctx.input("Proposals")
    dprobs = ctx.input("DraftProbs").astype(jnp.float32)
    tprobs = ctx.input("TargetProbs").astype(jnp.float32)
    seed = ctx.input("Seed").reshape(-1)
    pos = ctx.input("Pos").reshape(-1)
    k = int(ctx.attr("k"))
    end_id = int(ctx.attr("end_id"))
    max_len = int(ctx.attr("max_len"))
    greedy = bool(ctx.attr("greedy", True))
    base_seed = ctx.attr("base_seed", 0)
    tag = ctx.attr("noise_tag", 0)
    r = tprobs.shape[0]
    v = tprobs.shape[-1]

    posj = pos[:, None] + 1 + jnp.arange(k + 1)[None, :]  # [R, k+1]
    if k > 0:
        acc_keys = _lane_keys(
            _base_key(base_seed, tag + _TAG_ACCEPT), seed,
            posj[:, :k])
        u = jax.vmap(jax.vmap(jax.random.uniform))(acc_keys)  # [R,k]
        px = jnp.take_along_axis(tprobs[:, :k], props[..., None],
                                 axis=-1)[..., 0]
        qx = jnp.take_along_axis(dprobs, props[..., None],
                                 axis=-1)[..., 0]
        acc = u * qx < px
        a = jnp.cumprod(acc.astype(jnp.int64), axis=1).sum(axis=1)
        ai = jnp.clip(a, 0, k - 1)
        p_a = jnp.take_along_axis(
            tprobs, ai[:, None, None], axis=1)[:, 0]  # [R, V]
        q_a = jnp.take_along_axis(
            dprobs, ai[:, None, None], axis=1)[:, 0]
        resid = jnp.clip(p_a - q_a, 0.0, None)
        rs = resid.sum(axis=-1, keepdims=True)
        resid = jnp.where(rs > 0, resid / jnp.where(rs > 0, rs, 1.0),
                          p_a)
    else:
        a = jnp.zeros((r,), jnp.int64)
        resid = tprobs[:, 0]
    bonus = tprobs[:, k]
    corr_dist = jnp.where((a < k)[:, None], resid, bonus) if k > 0 \
        else bonus
    if greedy:
        corr_tok = jnp.argmax(corr_dist, axis=-1).astype(jnp.int64)
    else:
        corr_pos = pos + 1 + a  # the correction lands at this position
        corr_keys = _lane_keys(
            _base_key(base_seed, tag + _TAG_RESID), seed, corr_pos)
        corr_tok = jax.vmap(jax.random.categorical)(
            corr_keys, jnp.log(corr_dist + 1e-20)).astype(jnp.int64)

    cols = jnp.arange(k + 1)[None, :]
    if k > 0:
        toks = jnp.concatenate(
            [props.astype(jnp.int64), jnp.zeros((r, 1), jnp.int64)],
            axis=1)
    else:
        toks = jnp.zeros((r, 1), jnp.int64)
    toks = jnp.where(cols == a[:, None], corr_tok[:, None], toks)
    adv = a + 1
    # EOS clip: the lane finishes AT its first emitted end_id
    is_eos = (toks == end_id) & (cols < adv[:, None])
    eos_any = is_eos.any(axis=1)
    first_eos = jnp.argmax(is_eos, axis=1)
    adv = jnp.where(eos_any, first_eos + 1, adv)
    # room clip: never write past buffer position max_len-1
    room = jnp.clip(max_len - 1 - pos, 0, k + 1)
    adv = jnp.minimum(adv, room)
    fin = (eos_any & (first_eos + 1 <= adv)).astype(jnp.int64)
    accepted = jnp.minimum(a, adv)
    return {"Advance": adv.astype(jnp.int64), "Tokens": toks,
            "Accepted": accepted.astype(jnp.int64), "Fin": fin}
