"""Sequence ops over padded [batch, time, ...] + length representation.

Parity targets: reference paddle/fluid/operators/sequence_ops/ (~20 ops:
sequence_pool_op.cc, sequence_conv_op.cc, sequence_softmax_op.cc,
sequence_expand_op.cc, sequence_concat_op.cc, sequence_reverse_op.h,
sequence_pad_op.cc, sequence_unpad_op.cc, sequence_slice_op.cc,
sequence_enumerate_op.cc, sequence_reshape_op.cc) and the LoD machinery
they walk (framework/lod_tensor.h:110).

Design (SURVEY.md hard part (a)): LoD offset walking is replaced by mask/
segment arithmetic over static padded shapes -- every op is a dense
masked computation XLA can fuse and tile; no dynamic shapes ever reach
the compiler. `SeqLen` is an int32[batch] companion input.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op


def _mask(x, seq_len):
    """[B,T,...] validity mask from lengths -> same-rank float mask."""
    b, t = x.shape[0], x.shape[1]
    m = (jnp.arange(t)[None, :] < seq_len[:, None])
    return m.reshape((b, t) + (1,) * (x.ndim - 2)).astype(x.dtype)


@register_op("sequence_pool", stop_gradient_slots=("SeqLen",))
def sequence_pool(ctx):
    x = ctx.input("X")  # B,T,D
    seq_len = ctx.input("SeqLen")
    if seq_len is None:
        seq_len = jnp.full((x.shape[0],), x.shape[1], dtype=jnp.int32)
    ptype = ctx.attr("pooltype", "SUM").upper()
    m = _mask(x, seq_len)
    denom = jnp.maximum(seq_len.astype(x.dtype), 1)
    denom = denom.reshape((-1,) + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / denom
    elif ptype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(denom)
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(seq_len - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype(
                jnp.int32).repeat(x.shape[-1], axis=-1) if x.ndim == 3
            else idx[:, None].astype(jnp.int32), axis=1)
        out = out[:, 0]  # drop the gathered time axis for every rank
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"sequence_pool: unknown pooltype {ptype}")
    return {"Out": out,
            "MaxIndex": jnp.zeros(out.shape, dtype=jnp.int32)}


@register_op("sequence_softmax", stop_gradient_slots=("SeqLen",))
def sequence_softmax(ctx):
    x = ctx.input("X")  # B,T or B,T,1
    seq_len = ctx.input("SeqLen")
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    x2 = x[..., 0] if squeeze else x
    m = (jnp.arange(x2.shape[1])[None, :] < seq_len[:, None])
    logits = jnp.where(m, x2, jnp.finfo(x2.dtype).min)
    sm = jax.nn.softmax(logits, axis=1)
    sm = jnp.where(m, sm, 0.0)
    return sm[..., None] if squeeze else sm


@register_op("sequence_conv", stop_gradient_slots=("SeqLen",))
def sequence_conv(ctx):
    """Context-window conv (reference sequence_conv_op.cc): for each
    timestep, concat [t+start, t+start+len) rows (zero past boundaries)
    then project -- formulated as shifted adds feeding ONE matmul so the
    MXU does the work."""
    x = ctx.input("X")  # B,T,D
    w = ctx.input("Filter")  # ctxLen*D, M
    seq_len = ctx.input("SeqLen")
    clen = ctx.attr("contextLength", 3)
    cstart = ctx.attr("contextStart", -1)
    b, t, d = x.shape
    if seq_len is not None:
        x = x * _mask(x, seq_len)
    cols = []
    for i in range(clen):
        off = cstart + i
        if off < 0:
            pad = jnp.pad(x, ((0, 0), (-off, 0), (0, 0)))[:, :t]
        elif off > 0:
            pad = jnp.pad(x, ((0, 0), (0, off), (0, 0)))[:, off:]
        else:
            pad = x
        cols.append(pad)
    ctx_mat = jnp.concatenate(cols, axis=-1)  # B,T,clen*D
    out = jnp.einsum("btc,cm->btm", ctx_mat, w)
    if seq_len is not None:
        out = out * _mask(out, seq_len)
    return out


@register_op("sequence_expand", stop_gradient_slots=("SeqLen",))
def sequence_expand(ctx):
    """Broadcast per-sequence rows of X across Y's time dim (the common
    ref_level=0 use: expand [B,D] or [B,1,D] to [B,T,D])."""
    x = ctx.input("X")
    y = ctx.input("Y")
    t = y.shape[1]
    if x.ndim == 2:
        out = jnp.repeat(x[:, None, :], t, axis=1)
    elif x.shape[1] == 1:
        out = jnp.repeat(x, t, axis=1)
    else:
        out = x
    seq_len = ctx.input("SeqLen")
    if seq_len is not None:
        out = out * _mask(out, seq_len)
    return out


@register_op("sequence_concat", stop_gradient_slots=("SeqLen",))
def sequence_concat(ctx):
    """Concat along time (padded): place each input's valid prefix
    back-to-back per batch row."""
    xs = ctx.inputs("X")
    lens = ctx.inputs("SeqLen")
    if not lens or lens[0] is None:
        return jnp.concatenate(xs, axis=1)
    b = xs[0].shape[0]
    total_t = sum(x.shape[1] for x in xs)
    d_shape = xs[0].shape[2:]
    out = jnp.zeros((b, total_t) + d_shape, dtype=xs[0].dtype)
    offset = jnp.zeros((b,), dtype=jnp.int32)
    t_idx = jnp.arange(total_t)
    for x, l in zip(xs, lens):
        t = x.shape[1]
        # scatter rows: out[b, offset[b]+j] = x[b, j] for j < l[b]
        src_idx = jnp.arange(t)
        pos = offset[:, None] + src_idx[None, :]  # B,t
        valid = src_idx[None, :] < l[:, None]
        onehot = (t_idx[None, None, :] == pos[:, :, None]) \
            & valid[:, :, None]
        out = out + jnp.einsum(
            "bts,bt...->bs...", onehot.astype(x.dtype), x)
        offset = offset + l.astype(jnp.int32)
    return out


@register_op("sequence_reverse", stop_gradient_slots=("SeqLen",))
def sequence_reverse(ctx):
    x = ctx.input("X")
    seq_len = ctx.input("SeqLen")
    t = x.shape[1]
    if seq_len is None:
        return {"Y": jnp.flip(x, axis=1)}
    idx = jnp.arange(t)[None, :]
    rev = seq_len[:, None] - 1 - idx
    gather_idx = jnp.where(idx < seq_len[:, None], rev, idx)
    if x.ndim > 2:
        idx_full = jnp.broadcast_to(
            gather_idx.reshape(gather_idx.shape + (1,) * (x.ndim - 2))
            .astype(jnp.int32), (x.shape[0], t) + x.shape[2:])
    else:
        idx_full = gather_idx.astype(jnp.int32)
    out = jnp.take_along_axis(x, idx_full, axis=1)
    return {"Y": out}


@register_op("sequence_reshape")
def sequence_reshape(ctx):
    x = ctx.input("X")
    new_dim = ctx.attr("new_dim")
    b = x.shape[0]
    return x.reshape(b, -1, new_dim)


@register_op("sequence_pad", stop_gradient_slots=("SeqLen", "PadValue"))
def sequence_pad(ctx):
    x = ctx.input("X")
    seq_len = ctx.input("SeqLen")
    pad_value = ctx.input("PadValue")
    padded_len = ctx.attr("padded_length", -1)
    t = x.shape[1] if padded_len in (-1, None) else padded_len
    if t > x.shape[1]:
        x = jnp.pad(x, ((0, 0), (0, t - x.shape[1]))
                    + ((0, 0),) * (x.ndim - 2))
    elif t < x.shape[1]:
        x = x[:, :t]
    if seq_len is None:
        seq_len = jnp.full((x.shape[0],), t, dtype=jnp.int32)
    m = _mask(x, seq_len)
    pv = pad_value.reshape(()) if pad_value is not None else 0.0
    out = x * m + (1 - m) * pv
    return {"Out": out, "Length": seq_len.astype(jnp.int64)}


@register_op("sequence_unpad", stop_gradient_slots=("Length",))
def sequence_unpad(ctx):
    x = ctx.input("X")
    length = ctx.input("Length")
    m = _mask(x, length.astype(jnp.int32))
    return x * m


@register_op("sequence_slice", stop_gradient_slots=("Offset", "Length"))
def sequence_slice(ctx):
    x = ctx.input("X")  # B,T,...
    offset = ctx.input("Offset").reshape(-1).astype(jnp.int32)
    length = ctx.input("Length").reshape(-1).astype(jnp.int32)
    t = x.shape[1]
    idx = jnp.arange(t)[None, :]
    gidx = jnp.minimum(offset[:, None] + idx, t - 1)
    if x.ndim > 2:
        gidx_full = jnp.broadcast_to(
            gidx.reshape(gidx.shape + (1,) * (x.ndim - 2)).astype(
                jnp.int32), (x.shape[0], t) + x.shape[2:])
    else:
        gidx_full = gidx
    gat = jnp.take_along_axis(x, gidx_full, axis=1)
    m = (idx < length[:, None]).reshape(
        (x.shape[0], t) + (1,) * (x.ndim - 2)).astype(x.dtype)
    return gat * m


@register_op("sequence_enumerate", differentiable=False)
def sequence_enumerate(ctx):
    x = ctx.input("X")  # B,T int ids
    win = ctx.attr("win_size")
    pad = ctx.attr("pad_value", 0)
    if x.ndim == 3 and x.shape[-1] == 1:
        x = x[..., 0]
    b, t = x.shape
    outs = []
    for i in range(win):
        if i == 0:
            outs.append(x)
        else:
            outs.append(jnp.pad(x, ((0, 0), (0, i)),
                                constant_values=pad)[:, i:])
    return jnp.stack(outs, axis=-1)


@register_op("sequence_scatter", stop_gradient_slots=("Ids",))
def sequence_scatter(ctx):
    x = ctx.input("X")
    ids = ctx.input("Ids").astype(jnp.int32)
    upd = ctx.input("Updates")
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    b = x.shape[0]
    batch_idx = jnp.arange(b)[:, None].repeat(ids.shape[1], axis=1)
    return x.at[batch_idx, ids].add(upd)


@register_op("lod_reset", stop_gradient_slots=("Y",))
def lod_reset(ctx):
    # lengths live in the @SEQ_LEN companion; data passes through
    return ctx.input("X")


@register_op("shrink_memory")
def shrink_memory(ctx):
    return ctx.input("X")
