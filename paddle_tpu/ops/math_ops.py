"""Dense math, elementwise (fluid axis-broadcast semantics), activations,
reductions, comparisons.

Parity targets: reference paddle/fluid/operators/mul_op.cc, matmul_op.cc,
elementwise/elementwise_op_function.h (broadcast machinery),
activation_op.cc (~25 activations via functor registry),
reduce_ops/, cum_op era. On TPU the matmuls ride the MXU; everything
elementwise fuses into neighbours under XLA, replacing the reference's
explicit fuse passes and AVX/JIT kernels (operators/jit/).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op


# --------------------------------------------------------------------------
# matmul family
# --------------------------------------------------------------------------
def _flatten2d(x, num_col_dims):
    lead = int(np.prod(x.shape[:num_col_dims])) if num_col_dims else 1
    return jnp.reshape(x, (lead, -1))


@register_op("mul")
def mul(ctx):
    """reference mul_op.cc: flatten X/Y to 2-D then matmul."""
    x, y = ctx.input("X"), ctx.input("Y")
    xnc = ctx.attr("x_num_col_dims", 1)
    ync = ctx.attr("y_num_col_dims", 1)
    x2 = _flatten2d(x, xnc)
    y2 = jnp.reshape(y, (int(np.prod(y.shape[:ync])), -1))
    out = jnp.matmul(x2, y2)
    out_shape = x.shape[:xnc] + y.shape[ync:]
    return jnp.reshape(out, out_shape)


@register_op("matmul")
def matmul(ctx):
    """reference matmul_op.cc: batched matmul with transpose flags+alpha."""
    x, y = ctx.input("X"), ctx.input("Y")
    tx, ty = ctx.attr("transpose_X", False), ctx.attr("transpose_Y", False)
    alpha = ctx.attr("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :] if not tx else x[:, None]
    if y.ndim == 1:
        y = y[:, None] if not ty else y[None, :]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return out


@register_op("matmul_v2")
def matmul_v2(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    if ctx.attr("trans_x", False):
        x = jnp.swapaxes(x, -1, -2)
    if ctx.attr("trans_y", False):
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


@register_op("dot")
def dot(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    return jnp.sum(x * y, axis=-1, keepdims=True)


# --------------------------------------------------------------------------
# elementwise binary ops with fluid axis semantics
# (reference elementwise_op_function.h: Y broadcast against X from `axis`)
# --------------------------------------------------------------------------
def _broadcast_y(x, y, axis):
    if x.shape == y.shape:
        return y
    if axis == -1 or axis is None:
        axis = x.ndim - y.ndim
    # trim trailing 1s of y per fluid semantics
    yshape = list(y.shape)
    while yshape and yshape[-1] == 1 and len(yshape) > x.ndim - axis:
        yshape.pop()
    new_shape = [1] * axis + yshape + [1] * (x.ndim - axis - len(yshape))
    return jnp.reshape(y, new_shape)


def _ew(fn):
    def kernel(ctx):
        x, y = ctx.input("X"), ctx.input("Y")
        y = _broadcast_y(x, y, ctx.attr("axis", -1))
        return fn(x, y)

    return kernel


register_op("elementwise_add")(_ew(jnp.add))
register_op("elementwise_sub")(_ew(jnp.subtract))
register_op("elementwise_mul")(_ew(jnp.multiply))
register_op("elementwise_div")(_ew(jnp.divide))
register_op("elementwise_min")(_ew(jnp.minimum))
register_op("elementwise_max")(_ew(jnp.maximum))
register_op("elementwise_pow")(_ew(jnp.power))
register_op("elementwise_mod", differentiable=False)(_ew(jnp.mod))
register_op("elementwise_floordiv", differentiable=False)(
    _ew(jnp.floor_divide))


# --------------------------------------------------------------------------
# activations (reference activation_op.cc)
# --------------------------------------------------------------------------
def _unary(fn, type_name, differentiable=True):
    def kernel(ctx):
        return fn(ctx.input("X"))

    register_op(type_name, differentiable=differentiable)(kernel)
    return kernel


_unary(jax.nn.relu, "relu")
_unary(jax.nn.sigmoid, "sigmoid")
_unary(jnp.tanh, "tanh")
_unary(jnp.exp, "exp")
_unary(jnp.sqrt, "sqrt")
_unary(lambda x: jax.lax.rsqrt(x), "rsqrt")
_unary(jnp.abs, "abs")
_unary(jnp.log, "log")
_unary(jnp.square, "square")
_unary(jnp.floor, "floor", differentiable=False)
_unary(jnp.ceil, "ceil", differentiable=False)
_unary(jnp.round, "round", differentiable=False)
_unary(jnp.reciprocal, "reciprocal")
_unary(jax.nn.softplus, "softplus")
_unary(lambda x: x / (1 + jnp.abs(x)), "softsign")
_unary(jnp.sin, "sin")
_unary(jnp.cos, "cos")
_unary(jnp.arccos, "acos")
_unary(jnp.arcsin, "asin")
_unary(jnp.arctan, "atan")
_unary(lambda x: jax.nn.gelu(x, approximate=False), "gelu")
_unary(jnp.sign, "sign", differentiable=False)
_unary(jnp.logical_not, "logical_not", differentiable=False)


@register_op("gelu_approx")
def gelu_approx(ctx):
    return jax.nn.gelu(ctx.input("X"), approximate=True)


@register_op("leaky_relu")
def leaky_relu(ctx):
    return jax.nn.leaky_relu(ctx.input("X"), ctx.attr("alpha", 0.02))


@register_op("elu")
def elu(ctx):
    return jax.nn.elu(ctx.input("X"), ctx.attr("alpha", 1.0))


@register_op("relu6")
def relu6(ctx):
    return jnp.clip(ctx.input("X"), 0.0, ctx.attr("threshold", 6.0))


@register_op("pow")
def pow_op(ctx):
    return jnp.power(ctx.input("X"), ctx.attr("factor", 1.0))


@register_op("hard_sigmoid")
def hard_sigmoid(ctx):
    x = ctx.input("X")
    slope = ctx.attr("slope", 0.2)
    offset = ctx.attr("offset", 0.5)
    return jnp.clip(x * slope + offset, 0.0, 1.0)


@register_op("swish")
def swish(ctx):
    x = ctx.input("X")
    beta = ctx.attr("beta", 1.0)
    return x * jax.nn.sigmoid(beta * x)


@register_op("hard_swish")
def hard_swish(ctx):
    x = ctx.input("X")
    t = ctx.attr("threshold", 6.0)
    s = ctx.attr("scale", 6.0)
    o = ctx.attr("offset", 3.0)
    return x * jnp.clip(x + o, 0.0, t) / s


# --------------------------------------------------------------------------
# reductions (reference operators/reduce_ops/)
# --------------------------------------------------------------------------
def _reduce(fn, type_name, differentiable=True):
    def kernel(ctx):
        x = ctx.input("X")
        if ctx.attr("reduce_all", False):
            dims = None
        else:
            dims = tuple(d % x.ndim for d in ctx.attr("dim", [0]))
        return fn(x, axis=dims, keepdims=ctx.attr("keep_dim", False))

    register_op(type_name, differentiable=differentiable)(kernel)


_reduce(jnp.sum, "reduce_sum")
_reduce(jnp.mean, "reduce_mean")
_reduce(jnp.max, "reduce_max")
_reduce(jnp.min, "reduce_min")
_reduce(jnp.prod, "reduce_prod")
_reduce(jnp.all, "reduce_all", differentiable=False)
_reduce(jnp.any, "reduce_any", differentiable=False)


@register_op("mean")
def mean(ctx):
    # fluid mean outputs shape [1] (reference mean_op.cc)
    return jnp.mean(ctx.input("X")).reshape((1,))


@register_op("cumsum")
def cumsum(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    out = jnp.cumsum(x, axis=axis)
    if ctx.attr("exclusive", False):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        sl = [slice(None)] * x.ndim
        sl[axis] = slice(0, x.shape[axis])
        out = jnp.pad(out, pad)[tuple(sl)]
    if ctx.attr("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    return out


@register_op("frobenius_norm")
def frobenius_norm(ctx):
    x = ctx.input("X")
    dims = tuple(ctx.attr("dim", list(range(x.ndim))))
    return jnp.sqrt(jnp.sum(x * x, axis=dims,
                            keepdims=ctx.attr("keep_dim", False)))


@register_op("squared_l2_norm")
def squared_l2_norm(ctx):
    x = ctx.input("X")
    return jnp.sum(x * x).reshape((1,))


@register_op("p_norm")
def p_norm(ctx):
    x = ctx.input("X")
    p = ctx.attr("porder", 2.0)
    axis = ctx.attr("axis", -1)
    return jnp.sum(jnp.abs(x) ** p, axis=axis,
                   keepdims=ctx.attr("keepdim", False)) ** (1.0 / p)


# --------------------------------------------------------------------------
# comparisons / logical (reference operators/controlflow/compare_op.cc)
# --------------------------------------------------------------------------
def _cmp(fn, type_name):
    def kernel(ctx):
        x, y = ctx.input("X"), ctx.input("Y")
        return fn(x, y)

    register_op(type_name, differentiable=False)(kernel)


_cmp(jnp.less_equal, "less_equal")
_cmp(jnp.less, "less_than")
_cmp(jnp.greater_equal, "greater_equal")
_cmp(jnp.greater, "greater_than")
_cmp(jnp.equal, "equal")
_cmp(jnp.not_equal, "not_equal")
_cmp(jnp.logical_and, "logical_and")
_cmp(jnp.logical_or, "logical_or")
_cmp(jnp.logical_xor, "logical_xor")


@register_op("maximum")
def maximum(ctx):
    return jnp.maximum(ctx.input("X"), ctx.input("Y"))


@register_op("minimum")
def minimum(ctx):
    return jnp.minimum(ctx.input("X"), ctx.input("Y"))


@register_op("thresholded_relu")
def thresholded_relu(ctx):
    x = ctx.input("X")
    t = ctx.attr("threshold", 1.0)
    return jnp.where(x > t, x, jnp.zeros_like(x))


@register_op("stanh")
def stanh(ctx):
    """reference operators/activation_op.cc STanh:
    out = b * tanh(a * x) with a=scale_a, b=scale_b."""
    a = ctx.attr("scale_a", 0.67)
    b = ctx.attr("scale_b", 1.7159)
    return b * jnp.tanh(a * ctx.input("X"))
