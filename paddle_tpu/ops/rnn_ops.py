"""RNN cell + full-sequence ops lowered to lax.scan.

Parity targets: reference paddle/fluid/operators/lstm_op.cc (+math/
lstm_compute), gru_op.cc, gru_unit_op.cc, lstm_unit_op.cc,
cudnn_lstm_op.cu.cc. The reference interprets timesteps through the
dynamic-RNN machinery (recurrent_op.cc) or hands the loop to cuDNN; here
the time loop is a lax.scan that XLA compiles into one fused loop with
the gate matmuls on the MXU. Gradients come from the registry's generic
jax.vjp maker -- vjp differentiates straight through the scan, replacing
the reference's hand-written *_grad kernels.

Sequence lengths: computed timesteps past a row's length are masked so
state freezes (h_t = h_{t-1}) -- same numerics as LoD-packed batching.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op

_ACT = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
        "relu": jax.nn.relu, "identity": lambda x: x}


@register_op("lstm", stop_gradient_slots=("SeqLen",))
def lstm(ctx):
    """reference lstm_op.cc: Input [B,T,4H] (pre-projected x W_x),
    recurrent Weight [H,4H], Bias [1,4H(+3H peepholes)].
    Gate order (reference math/detail/lstm_kernel.h): i, f, c, o."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    bias = ctx.input("Bias")
    seq_len = ctx.input("SeqLen")
    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    use_peepholes = ctx.attr("use_peepholes", True)
    is_reverse = ctx.attr("is_reverse", False)
    act_gate = _ACT[ctx.attr("gate_activation", "sigmoid")]
    act_cell = _ACT[ctx.attr("cell_activation", "tanh")]
    act_cand = _ACT[ctx.attr("candidate_activation", "tanh")]
    b_sz, t, four_h = x.shape
    h_dim = four_h // 4
    if bias is not None:
        gate_bias = bias[..., :4 * h_dim].reshape(1, 4 * h_dim)
        x = x + gate_bias[None]
        if use_peepholes:
            peep = bias[..., 4 * h_dim:].reshape(3 * h_dim)
            w_ic, w_fc, w_oc = (peep[:h_dim], peep[h_dim:2 * h_dim],
                                peep[2 * h_dim:])
        else:
            w_ic = w_fc = w_oc = None
    else:
        w_ic = w_fc = w_oc = None
    if seq_len is None:
        seq_len = jnp.full((b_sz,), t, dtype=jnp.int32)
    h_init = h0 if h0 is not None else jnp.zeros((b_sz, h_dim), x.dtype)
    c_init = c0 if c0 is not None else jnp.zeros((b_sz, h_dim), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)  # T,B,4H
    steps = jnp.arange(t)
    if is_reverse:
        xs = xs[::-1]
        steps = steps[::-1]

    def cell(carry, inp):
        h_prev, c_prev = carry
        xt, step = inp
        gates = xt + h_prev @ w
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i = act_gate(gi)
        f = act_gate(gf)
        cand = act_cand(gc)
        c = f * c_prev + i * cand
        if w_oc is not None:
            go = go + c * w_oc
        o = act_gate(go)
        h = o * act_cell(c)
        valid = (step < seq_len)[:, None].astype(x.dtype)
        h = valid * h + (1 - valid) * h_prev
        c = valid * c + (1 - valid) * c_prev
        return (h, c), (h, c)

    (_, _), (hs, cs) = jax.lax.scan(cell, (h_init, c_init), (xs, steps))
    if is_reverse:
        hs, cs = hs[::-1], cs[::-1]
    return {"Hidden": jnp.swapaxes(hs, 0, 1),
            "Cell": jnp.swapaxes(cs, 0, 1)}


@register_op("gru", stop_gradient_slots=("SeqLen",))
def gru(ctx):
    """reference gru_op.cc: Input [B,T,3H] pre-projected, Weight [H,3H]
    laid out [W_update|W_reset | W_candidate], Bias [1,3H].
    Gate order: update, reset, candidate (math/detail/gru_kernel.h)."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    bias = ctx.input("Bias")
    seq_len = ctx.input("SeqLen")
    h0 = ctx.input("H0")
    is_reverse = ctx.attr("is_reverse", False)
    origin_mode = ctx.attr("origin_mode", False)
    act_gate = _ACT[ctx.attr("gate_activation", "sigmoid")]
    act_cand = _ACT[ctx.attr("activation", "tanh")]
    b_sz, t, three_h = x.shape
    h_dim = three_h // 3
    if bias is not None:
        x = x + bias.reshape(1, 1, three_h)
    w_rz = w[:, :2 * h_dim]
    w_c = w[:, 2 * h_dim:]
    if seq_len is None:
        seq_len = jnp.full((b_sz,), t, dtype=jnp.int32)
    h_init = h0 if h0 is not None else jnp.zeros((b_sz, h_dim), x.dtype)
    xs = jnp.swapaxes(x, 0, 1)
    steps = jnp.arange(t)
    if is_reverse:
        xs = xs[::-1]
        steps = steps[::-1]

    def cell(h_prev, inp):
        xt, step = inp
        xu, xr, xc = jnp.split(xt, 3, axis=-1)
        rz = jnp.concatenate([xu, xr], -1) + h_prev @ w_rz
        u = act_gate(rz[:, :h_dim])
        r = act_gate(rz[:, h_dim:])
        cand = act_cand(xc + (r * h_prev) @ w_c)
        if origin_mode:
            h = u * h_prev + (1 - u) * cand
        else:
            h = (1 - u) * h_prev + u * cand
        valid = (step < seq_len)[:, None].astype(x.dtype)
        h = valid * h + (1 - valid) * h_prev
        return h, h

    _, hs = jax.lax.scan(cell, h_init, (xs, steps))
    if is_reverse:
        hs = hs[::-1]
    return {"Hidden": jnp.swapaxes(hs, 0, 1)}


@register_op("gru_unit")
def gru_unit(ctx):
    """Single GRU step (reference gru_unit_op.cc)."""
    x = ctx.input("Input")  # B,3H
    h_prev = ctx.input("HiddenPrev")
    w = ctx.input("Weight")
    bias = ctx.input("Bias")
    origin_mode = ctx.attr("origin_mode", False)
    act_gate = _ACT.get(ctx.attr("gate_activation", "sigmoid"),
                        jax.nn.sigmoid)
    act_cand = _ACT.get(ctx.attr("activation", "tanh"), jnp.tanh)
    h_dim = h_prev.shape[-1]
    if bias is not None:
        x = x + bias.reshape(1, -1)
    xu, xr, xc = jnp.split(x, 3, axis=-1)
    rz = jnp.concatenate([xu, xr], -1) + h_prev @ w[:, :2 * h_dim]
    u = act_gate(rz[:, :h_dim])
    r = act_gate(rz[:, h_dim:])
    reset_h = r * h_prev
    cand = act_cand(xc + reset_h @ w[:, 2 * h_dim:])
    if origin_mode:
        h = u * h_prev + (1 - u) * cand
    else:
        h = (1 - u) * h_prev + u * cand
    return {"Gate": jnp.concatenate([u, r, cand], -1),
            "ResetHiddenPrev": reset_h, "Hidden": h}


@register_op("lstm_unit")
def lstm_unit(ctx):
    """Single LSTM step (reference lstm_unit_op.cc): X=[B,4H] gates
    (i,f,c,o after fc), C_prev -> C, H."""
    x = ctx.input("X")
    c_prev = ctx.input("C_prev")
    forget_bias = ctx.attr("forget_bias", 0.0)
    gi, gf, gc, go = jnp.split(x, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    return {"C": c, "H": h}


@register_op("cudnn_lstm", needs_rng=True)
def cudnn_lstm(ctx):
    """reference cudnn_lstm_op.cc / cudnn_rnn_cache.h: multi-layer
    LSTM over seq-major input with cuDNN's canonically PACKED flat
    weight vector. TPU lowering: unpack W into per-layer (Wx, Wh,
    bx, bh) and run the same scan the `lstm` op uses -- one XLA
    program, no cuDNN. Packing layout (cudnnGetRNNLinLayerMatrixParams
    order): per PSEUDO-layer the 8 matrices [Wi Wf Wc Wo | Ri Rf Rc Ro],
    then per pseudo-layer the 8 bias vectors in the same order. A
    pseudo-layer is (layer, direction) with direction minor — for
    is_bidirec the order is l0-fwd, l0-bwd, l1-fwd, l1-bwd, ... and
    layers past the first consume the 2H concat of both directions.
    Gate order i, f, c(candidate), o. Input [T, B, I] (seq-major, the
    cuDNN convention), InitH/InitC [L*dirs, B, H]; Out is [T, B,
    H*dirs].
    """
    x = ctx.input("Input")            # [T, B, I]
    w = ctx.input("W").reshape(-1)
    h0 = ctx.input("InitH")
    c0 = ctx.input("InitC")
    hidden = int(ctx.attr("hidden_size", 100))
    in_size = int(ctx.attr("input_size", x.shape[-1]))
    layers = int(ctx.attr("num_layers", 1))
    dropout_p = float(ctx.attr("dropout_prob", 0.0))
    is_test = ctx.attr("is_test", False)
    dirs = 2 if ctx.attr("is_bidirec", False) else 1
    t, b, _ = x.shape
    h = hidden

    # unpack the cuDNN canonical flat weights, pseudo-layer major
    mats = []
    off = 0
    for pl in range(layers * dirs):
        layer = pl // dirs
        isz = in_size if layer == 0 else h * dirs
        wx = w[off:off + 4 * h * isz].reshape(4 * h, isz)
        off += 4 * h * isz
        wh = w[off:off + 4 * h * h].reshape(4 * h, h)
        off += 4 * h * h
        mats.append((wx, wh))
    biases = []
    for pl in range(layers * dirs):
        bx = w[off:off + 4 * h]
        off += 4 * h
        bh = w[off:off + 4 * h]
        off += 4 * h
        biases.append(bx + bh)

    if h0 is None:
        h0 = jnp.zeros((layers * dirs, b, h), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((layers * dirs, b, h), x.dtype)

    def run_direction(seq, pl, reverse):
        wx, wh = mats[pl]
        pre = jnp.einsum("tbi,gi->tbg", seq, wx) + biases[pl]
        if reverse:
            pre = pre[::-1]

        def cell(carry, xt):
            hp, cp = carry
            gates = xt + hp @ wh.T
            gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(gi)
            f = jax.nn.sigmoid(gf)
            c = f * cp + i * jnp.tanh(gc)
            o = jax.nn.sigmoid(go)
            hh = o * jnp.tanh(c)
            return (hh, c), hh

        (hT, cT), hs = jax.lax.scan(cell, (h0[pl], c0[pl]), pre)
        return (hs[::-1] if reverse else hs), hT, cT

    seq = x
    last_h, last_c = [], []
    for l in range(layers):
        outs = []
        for d in range(dirs):
            pl = l * dirs + d
            hs, hT, cT = run_direction(seq, pl, reverse=(d == 1))
            outs.append(hs)
            last_h.append(hT)
            last_c.append(cT)
        seq = outs[0] if dirs == 1 else jnp.concatenate(outs, -1)
        if dropout_p and not is_test and l < layers - 1:
            keep = jax.random.bernoulli(ctx.rng(), 1.0 - dropout_p,
                                        seq.shape)
            seq = seq * keep / (1.0 - dropout_p)
    return {"Out": seq, "last_h": jnp.stack(last_h),
            "last_c": jnp.stack(last_c)}
