"""Control-flow op kernels: while / conditional_block / tensor arrays.

TPU-native counterparts of the reference's sub-block-interpreting ops
(reference operators/controlflow/while_op.cc — runs the sub-block via an
inner Executor per iteration — and conditional_block_op.cc,
tensor_array_read_write_op.cc). Here the sub-block is *traced* into the
enclosing XLA computation: `while` lowers to lax.while_loop over an
explicit carry (the vars the body mutates), `conditional_block` to
lax.cond over both traced branches. Data-dependent trip counts stay on
device; data-dependent *shapes* remain illegal (XLA static-shape rule).

Tensor arrays are trace-time Python lists of traced values: writes
append in program order, reads index statically when possible and fall
back to a stacked dynamic gather. Inside a lax.while_loop body the carry
must be jax types, so arrays cannot be loop-carried — scan-based RNNs
(ops/rnn_ops.py) are the supported dynamic-sequence path, matching the
SURVEY §5 obligation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op, run_op


class TensorArray(list):
    """Marker type for LoDTensorArray values living in the executor env."""


def _no_infer(op, block):
    return None


@register_op("while", differentiable=False, infer_shape=_no_infer,
             stop_gradient_slots=("Condition",))
def while_op(ctx):
    """lax.while_loop over the traced sub-block.

    inputs: Condition (bool, must be among the carried writes or the
    loop never ends), X = externals (read-only), Init = carried initial
    values. outputs: Out = carried finals. attrs: sub_block, carried,
    externals.
    """
    sub = ctx.attr("sub_block")
    carried = list(ctx.attr("carried"))
    externals = list(ctx.attr("externals"))
    cond_name = ctx.op.inputs["Condition"][0]
    ext_env = dict(zip(externals, ctx.inputs("X")))
    init = tuple(ctx.inputs("Init"))

    def as_pred(v):
        return jnp.reshape(v, ()).astype(bool)

    def cond_fn(carry):
        env = dict(ext_env)
        env.update(zip(carried, carry))
        if cond_name in env:
            return as_pred(env[cond_name])
        raise ValueError(
            f"while: condition var {cond_name!r} is neither carried nor "
            f"external — the loop body must update it")

    def body_fn(carry):
        env = dict(ext_env)
        env.update(zip(carried, carry))
        for op in sub.ops:
            run_op(op, env, rng_cell=None, rng_salt=op._uid)
        return tuple(env[n] for n in carried)

    final = lax.while_loop(cond_fn, body_fn, init)
    return {"Out": list(final)}


@register_op("run_block_if", differentiable=False,
             infer_shape=_no_infer, stop_gradient_slots=("Condition",))
def run_block_if(ctx):
    """Run a sub-block's ops iff Condition, carrying the vars the block
    mutates (the multi-output sibling of conditional_block: lax.cond
    with identity false branch). Used by GradientMergeOptimizer to gate
    the optimize section on the k-th micro-step (reference
    ir/multi_batch_merge_pass.cc repeats fwd/bwd k times in the SSA
    graph then applies optimize once; here the SAME compiled program
    runs every step and the apply is a cond -- XLA-friendly, no
    program switching).

    inputs: Condition, X = externals (read-only), Init = carried
    initial values. outputs: Out = carried finals. attrs: sub_block,
    carried, externals.
    """
    sub = ctx.attr("sub_block")
    carried = list(ctx.attr("carried"))
    externals = list(ctx.attr("externals"))
    ext_env = dict(zip(externals, ctx.inputs("X")))
    init = tuple(ctx.inputs("Init"))
    pred = jnp.reshape(ctx.input("Condition"), ()).astype(bool)

    def true_fn(carry):
        env = dict(ext_env)
        env.update(zip(carried, carry))
        for op in sub.ops:
            run_op(op, env, rng_cell=None, rng_salt=op._uid)
        return tuple(env[n] for n in carried)

    def false_fn(carry):
        return carry

    final = lax.cond(pred, true_fn, false_fn, init)
    return {"Out": list(final)}


@register_op("conditional_block", infer_shape=_no_infer,
             stop_gradient_slots=("Condition",))
def conditional_block(ctx):
    """lax.cond over two traced branches (reference
    conditional_block_op.cc; the fluid layers.cond API)."""
    tb = ctx.attr("true_block")
    fb = ctx.attr("false_block")
    t_out = ctx.attr("true_out")
    f_out = ctx.attr("false_out")
    x_names = list(ctx.op.inputs.get("X", []))
    x_vals = ctx.inputs("X")
    pred = jnp.reshape(ctx.input("Condition"), ()).astype(bool)

    def branch(blk, out_name):
        def f(vals):
            env = dict(zip(x_names, vals))
            for op in blk.ops:
                run_op(op, env, rng_cell=None, rng_salt=op._uid)
            return env[out_name]

        return f

    if f_out is None:
        raise ValueError("cond: both true_fn and false_fn must return a "
                         "value (XLA branches need matching outputs)")
    return lax.cond(pred, branch(tb, t_out), branch(fb, f_out), x_vals)


# --------------------------------------------------------------------------
# LoDTensorArray ops (reference tensor_array_read_write_op.cc,
# lod_array_length_op.cc). Arrays are trace-time lists (see module doc).
# --------------------------------------------------------------------------
def _static_index(i):
    """Extract a Python int from a traced index if it is concrete."""
    try:
        return int(i)
    except Exception:
        return None


@register_op("create_array", differentiable=False, infer_shape=_no_infer)
def create_array_op(ctx):
    return {"Out": [TensorArray()]}


@register_op("write_to_array", differentiable=False,
             infer_shape=_no_infer, stop_gradient_slots=("I",))
def write_to_array(ctx):
    x = ctx.input("X")
    prev = ctx.input("Array")
    arr = TensorArray(prev) if isinstance(prev, list) else TensorArray()
    i = ctx.input("I")
    idx = _static_index(i) if i is not None else len(arr)
    if idx is None:
        arr.append(x)  # dynamic index: append in program order
    else:
        # grow to idx+1 like the reference WriteToArrayOp, so an
        # out-of-order static write lands at its index (gap slots hold
        # zeros until their own write arrives)
        while len(arr) < idx:
            arr.append(jnp.zeros_like(x))
        if idx < len(arr):
            arr[idx] = x
        else:
            arr.append(x)
    return {"Out": [arr]}


@register_op("read_from_array", differentiable=False,
             infer_shape=_no_infer, stop_gradient_slots=("I",))
def read_from_array(ctx):
    arr = ctx.input("X")
    if not isinstance(arr, list):
        raise TypeError("read_from_array: input is not a tensor array")
    i = ctx.input("I")
    idx = _static_index(i)
    if idx is not None:
        return {"Out": arr[idx]}
    # dynamic index: stack (uniform shapes) and gather on device
    stacked = jnp.stack(list(arr))
    return {"Out": stacked[jnp.reshape(i, ()).astype(jnp.int32)]}


@register_op("lod_array_length", differentiable=False,
             infer_shape=_no_infer)
def lod_array_length(ctx):
    arr = ctx.input("X")
    return {"Out": jnp.asarray([len(arr)], dtype=jnp.int64)}
