"""Misc / vision ops: prelu, maxout, interpolation, roi ops, shuffles.

Parity targets: reference paddle/fluid/operators/prelu_op.cc, maxout_op.cc,
interpolate_op.cc (bilinear/nearest), grid_sampler_op.cc, affine_grid_op.cc,
affine_channel_op.cc, shuffle_channel_op.cc, pixel_shuffle_op.cc,
roi_pool_op.cc, roi_align_op.cc, psroi_pool_op.cc, row_conv_op.cc,
temporal_shift_op.cc, unfold_op.cc, im2sequence_op.cc, multiplex_op.cc,
label_smooth_op.cc, cos_sim_op.cc, sampling_id_op.cc, spectral_norm_op.cc.
All dense jnp formulations that XLA maps to MXU/VPU; gather-heavy roi ops
use vectorized one_hot matmuls where beneficial.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op


@register_op("prelu")
def prelu(ctx):
    x = ctx.input("X")
    alpha = ctx.input("Alpha")
    mode = ctx.attr("mode", "all")
    if mode == "all":
        a = alpha.reshape(())
    elif mode == "channel":
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:
        a = alpha.reshape((1,) + x.shape[1:])
    return jnp.where(x > 0, x, a * x)


@register_op("maxout")
def maxout(ctx):
    x = ctx.input("X")  # NCHW
    g = ctx.attr("groups")
    n, c, h, w = x.shape
    return x.reshape(n, c // g, g, h, w).max(axis=2)


@register_op("soft_relu")
def soft_relu(ctx):
    x = ctx.input("X")
    t = ctx.attr("threshold", 40.0)
    return jnp.log1p(jnp.exp(jnp.clip(x, -t, t)))


@register_op("brelu")
def brelu(ctx):
    return jnp.clip(ctx.input("X"), ctx.attr("t_min", 0.0),
                    ctx.attr("t_max", 24.0))


@register_op("label_smooth", stop_gradient_slots=("PriorDist",))
def label_smooth(ctx):
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 0.1)
    prior = ctx.input("PriorDist")
    k = x.shape[-1]
    if prior is not None:
        return (1 - eps) * x + eps * prior.reshape((1,) * (x.ndim - 1)
                                                   + (k,))
    return (1 - eps) * x + eps / k


@register_op("cos_sim")
def cos_sim(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    return {"Out": out, "XNorm": xn, "YNorm": yn}


@register_op("dice_loss", stop_gradient_slots=("Label",))
def dice_loss(ctx):
    x = ctx.input("X")
    label = ctx.input("Label").astype(x.dtype)
    eps = ctx.attr("epsilon", 1e-5)
    reduce_dims = tuple(range(1, x.ndim))
    inter = 2.0 * jnp.sum(x * label, axis=reduce_dims)
    union = jnp.sum(x, axis=reduce_dims) + jnp.sum(label,
                                                   axis=reduce_dims)
    return (1.0 - (inter + eps) / (union + eps)).mean().reshape(1)


@register_op("npair_loss", stop_gradient_slots=("Labels",))
def npair_loss(ctx):
    a, p = ctx.input("Anchor"), ctx.input("Positive")
    labels = ctx.input("Labels").reshape(-1)
    l2 = ctx.attr("l2_reg", 0.002)
    sim = a @ p.T
    eq = (labels[:, None] == labels[None, :]).astype(a.dtype)
    tgt = eq / jnp.sum(eq, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    xent = -jnp.sum(tgt * logp, axis=1).mean()
    reg = l2 * (jnp.mean(jnp.sum(a * a, axis=1))
                + jnp.mean(jnp.sum(p * p, axis=1)))
    return (xent + reg).reshape(1)


@register_op("interpolate")
def interpolate(ctx):
    x = ctx.input("X")  # NCHW
    oh, ow = ctx.attr("out_h"), ctx.attr("out_w")
    method = ctx.attr("interp_method", "bilinear")
    align = ctx.attr("align_corners", True)
    n, c, h, w = x.shape
    if method == "nearest":
        ih = (jnp.arange(oh) * (h / oh)).astype(jnp.int32)
        iw = (jnp.arange(ow) * (w / ow)).astype(jnp.int32)
        return x[:, :, ih][:, :, :, iw]
    # bilinear
    if align and oh > 1:
        ys = jnp.linspace(0.0, h - 1.0, oh)
    else:
        ys = (jnp.arange(oh) + 0.5) * h / oh - 0.5
    if align and ow > 1:
        xs = jnp.linspace(0.0, w - 1.0, ow)
    else:
        xs = (jnp.arange(ow) + 0.5) * w / ow - 0.5
    ys = jnp.clip(ys, 0, h - 1)
    xs = jnp.clip(xs, 0, w - 1)
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, None, :, None]
    wx = (xs - x0)[None, None, None, :]
    v00 = x[:, :, y0][:, :, :, x0]
    v01 = x[:, :, y0][:, :, :, x1]
    v10 = x[:, :, y1][:, :, :, x0]
    v11 = x[:, :, y1][:, :, :, x1]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


@register_op("grid_sampler")
def grid_sampler(ctx):
    x = ctx.input("X")  # NCHW
    grid = ctx.input("Grid")  # NHW2 in [-1, 1]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1) * (w - 1) / 2
    gy = (grid[..., 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx = gx - x0
    wy = gy - y0

    def sample(yi, xi):
        valid = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w))
        yi = jnp.clip(yi, 0, h - 1)
        xi = jnp.clip(xi, 0, w - 1)
        batch = jnp.arange(n)[:, None, None]
        v = x[batch, :, yi, xi]  # N,H,W,C
        return v * valid[..., None]

    v00 = sample(y0, x0)
    v01 = sample(y0, x1)
    v10 = sample(y1, x0)
    v11 = sample(y1, x1)
    out = (v00 * ((1 - wy) * (1 - wx))[..., None]
           + v01 * ((1 - wy) * wx)[..., None]
           + v10 * (wy * (1 - wx))[..., None]
           + v11 * (wy * wx)[..., None])
    return {"Output": jnp.transpose(out, (0, 3, 1, 2))}


@register_op("affine_grid")
def affine_grid(ctx):
    theta = ctx.input("Theta")  # N,2,3
    shape = ctx.attr("output_shape")
    n, _, h, w = shape if len(shape) == 4 else (theta.shape[0], 1,
                                                shape[0], shape[1])
    ys = jnp.linspace(-1, 1, h)
    xs = jnp.linspace(-1, 1, w)
    gx, gy = jnp.meshgrid(xs, ys)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # HW,3
    out = jnp.einsum("nij,kj->nki", theta, base)  # N,HW,2
    return {"Output": out.reshape(theta.shape[0], h, w, 2)}


@register_op("affine_channel")
def affine_channel(ctx):
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    layout = ctx.attr("data_layout", "NCHW")
    shape = (1, -1) + (1,) * (x.ndim - 2) if layout == "NCHW" \
        else (1,) * (x.ndim - 1) + (-1,)
    return x * scale.reshape(shape) + bias.reshape(shape)


@register_op("shuffle_channel")
def shuffle_channel(ctx):
    x = ctx.input("X")
    g = ctx.attr("group")
    n, c, h, w = x.shape
    return x.reshape(n, g, c // g, h, w).swapaxes(1, 2).reshape(x.shape)


@register_op("pixel_shuffle")
def pixel_shuffle(ctx):
    x = ctx.input("X")
    r = ctx.attr("upscale_factor")
    n, c, h, w = x.shape
    oc = c // (r * r)
    x = x.reshape(n, oc, r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, oc, h * r, w * r)


@register_op("pixel_unshuffle")
def pixel_unshuffle(ctx):
    x = ctx.input("X")
    r = ctx.attr("downscale_factor")
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return x.reshape(n, c * r * r, h // r, w // r)


@register_op("multiplex", stop_gradient_slots=("Ids",))
def multiplex(ctx):
    xs = jnp.stack(ctx.inputs("X"), axis=0)  # K,N,D
    ids = ctx.input("Ids").reshape(-1).astype(jnp.int32)
    rows = jnp.arange(ids.shape[0])
    return xs[ids, rows]


@register_op("sampling_id", differentiable=False, needs_rng=True)
def sampling_id(ctx):
    x = ctx.input("X")  # N,K probabilities
    key = ctx.rng()
    return jax.random.categorical(key, jnp.log(x + 1e-20),
                                  axis=-1).astype(jnp.int32)


@register_op("row_conv")
def row_conv(ctx):
    x = ctx.input("X")  # N,T,D (batched) -- lookahead conv
    w = ctx.input("Filter")  # (ctx+1),D
    k = w.shape[0]
    t = x.shape[1]
    pad = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(pad[:, i:i + t] * w[i][None, None, :] for i in range(k))
    return out


@register_op("temporal_shift")
def temporal_shift(ctx):
    x = ctx.input("X")  # NT,C,H,W
    seg = ctx.attr("seg_num")
    ratio = ctx.attr("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // seg
    x5 = x.reshape(n, seg, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    # reference temporal_shift_op.h:60-66: channels < c1 read
    # src_it = it-1 (the PAST frame), channels [c1,c2) read it+1
    past = jnp.pad(x5[:, :-1, :c1], ((0, 0), (1, 0), (0, 0), (0, 0),
                                     (0, 0)))
    future = jnp.pad(x5[:, 1:, c1:c2], ((0, 0), (0, 1), (0, 0), (0, 0),
                                        (0, 0)))
    keep = x5[:, :, c2:]
    return jnp.concatenate([past, future, keep],
                           axis=2).reshape(x.shape)


@register_op("unfold")
def unfold(ctx):
    x = ctx.input("X")  # N,C,H,W
    ks = ctx.attr("kernel_sizes")
    st = ctx.attr("strides", [1, 1])
    pd = ctx.attr("paddings", [0, 0])
    dl = ctx.attr("dilations", [1, 1])
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, ks, st, [(pd[0], pd[0]), (pd[1], pd[1])],
        rhs_dilation=dl, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # N, C*kh*kw, OH, OW -> N, C*kh*kw, OH*OW
    return patches.reshape(n, patches.shape[1], -1)


@register_op("im2sequence")
def im2sequence(ctx):
    x = ctx.input("X")
    ks = ctx.attr("kernels")
    st = ctx.attr("strides", [1, 1])
    pd = ctx.attr("paddings", [0, 0, 0, 0])
    patches = jax.lax.conv_general_dilated_patches(
        x, ks, st, [(pd[0], pd[2]), (pd[1], pd[3])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n, ckk, oh, ow = patches.shape
    return patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, ckk)


@register_op("spectral_norm")
def spectral_norm(ctx):
    w = ctx.input("Weight")
    u = ctx.input("U")
    v = ctx.input("V")
    dim = ctx.attr("dim", 0)
    iters = ctx.attr("power_iters", 1)
    eps = ctx.attr("eps", 1e-12)
    wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    for _ in range(max(iters, 0)):
        v = wm.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wm @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ wm @ v
    return w / sigma


def _roi_common(ctx):
    x = ctx.input("X")  # N,C,H,W
    rois = ctx.input("ROIs")  # R,4 (x1,y1,x2,y2)
    scale = ctx.attr("spatial_scale", 1.0)
    ph = ctx.attr("pooled_height", 1)
    pw = ctx.attr("pooled_width", 1)
    return x, rois, scale, ph, pw


@register_op("roi_align", stop_gradient_slots=("ROIs",))
def roi_align(ctx):
    x, rois, scale, ph, pw = _roi_common(ctx)
    # bin-center bilinear sampling, vectorized over rois (single image)
    x1s = rois[:, 0] * scale
    y1s = rois[:, 1] * scale
    x2s = rois[:, 2] * scale
    y2s = rois[:, 3] * scale
    rh = jnp.maximum(y2s - y1s, 1.0) / ph
    rw = jnp.maximum(x2s - x1s, 1.0) / pw
    # sample center points per bin
    py = y1s[:, None] + rh[:, None] * (jnp.arange(ph)[None, :] + 0.5)
    px = x1s[:, None] + rw[:, None] * (jnp.arange(pw)[None, :] + 0.5)
    py = jnp.clip(py, 0, x.shape[2] - 1)
    px = jnp.clip(px, 0, x.shape[3] - 1)
    y0 = jnp.floor(py).astype(jnp.int32)
    x0 = jnp.floor(px).astype(jnp.int32)
    y1c = jnp.minimum(y0 + 1, x.shape[2] - 1)
    x1c = jnp.minimum(x0 + 1, x.shape[3] - 1)
    wy = py - y0
    wx = px - x0
    feat = x[0]  # C,H,W

    def gat(yy, xx):
        # yy: R,PH  xx: R,PW -> C,R,PH,PW
        return feat[:, yy[:, :, None], xx[:, None, :]]

    v00 = gat(y0, x0)
    v01 = gat(y0, x1c)
    v10 = gat(y1c, x0)
    v11 = gat(y1c, x1c)
    wy_ = wy[None, :, :, None]
    wx_ = wx[None, :, None, :]
    out = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
           + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
    return jnp.transpose(out, (1, 0, 2, 3))  # R,C,PH,PW


@register_op("roi_pool", stop_gradient_slots=("ROIs",))
def roi_pool(ctx):
    x, rois, scale, ph, pw = _roi_common(ctx)
    n, c, h, w = x.shape
    feat = x[0]
    x1 = jnp.round(rois[:, 0] * scale).astype(jnp.int32)
    y1 = jnp.round(rois[:, 1] * scale).astype(jnp.int32)
    x2 = jnp.round(rois[:, 2] * scale).astype(jnp.int32)
    y2 = jnp.round(rois[:, 3] * scale).astype(jnp.int32)
    rh = jnp.maximum(y2 - y1 + 1, 1) / ph
    rw = jnp.maximum(x2 - x1 + 1, 1) / pw
    hs = jnp.arange(h)
    ws = jnp.arange(w)
    outs = []
    # bin membership masks (R,PH,H) x (R,PW,W): max over masked region
    yb0 = y1[:, None] + jnp.floor(jnp.arange(ph)[None, :] * rh[:, None])
    yb1 = y1[:, None] + jnp.ceil((jnp.arange(ph)[None, :] + 1)
                                 * rh[:, None])
    xb0 = x1[:, None] + jnp.floor(jnp.arange(pw)[None, :] * rw[:, None])
    xb1 = x1[:, None] + jnp.ceil((jnp.arange(pw)[None, :] + 1)
                                 * rw[:, None])
    ymask = ((hs[None, None, :] >= yb0[:, :, None])
             & (hs[None, None, :] < yb1[:, :, None]))  # R,PH,H
    xmask = ((ws[None, None, :] >= xb0[:, :, None])
             & (ws[None, None, :] < xb1[:, :, None]))  # R,PW,W
    neg = jnp.finfo(feat.dtype).min
    # C,R,PH,PW via masked max: expand (C,1,1,H,W)
    f = feat[:, None, None, :, :]
    m = (ymask[None, :, :, None, :, None]
         & xmask[None, :, None, :, None, :])  # 1,R,PH,PW,H,W
    fm = jnp.where(m, f[:, :, :, None, :, :], neg)
    out = fm.max(axis=(4, 5))  # C,R,PH,PW
    res = jnp.transpose(out, (1, 0, 2, 3))
    return {"Out": res, "Argmax": jnp.zeros(res.shape, dtype=jnp.int32)}


@register_op("psroi_pool", stop_gradient_slots=("ROIs",))
def psroi_pool(ctx):
    x, rois, scale, _, _ = _roi_common(ctx)
    ph = ctx.attr("pooled_height")
    pw = ctx.attr("pooled_width")
    oc = ctx.attr("output_channels")
    feat = x[0]  # C,H,W with C = oc*ph*pw
    h, w = feat.shape[1], feat.shape[2]
    r = rois.shape[0]
    x1 = rois[:, 0] * scale
    y1 = rois[:, 1] * scale
    x2 = rois[:, 2] * scale
    y2 = rois[:, 3] * scale
    rh = jnp.maximum(y2 - y1, 0.1) / ph
    rw = jnp.maximum(x2 - x1, 0.1) / pw
    py = jnp.clip((y1[:, None] + rh[:, None]
                   * (jnp.arange(ph)[None, :] + 0.5)).astype(jnp.int32),
                  0, h - 1)
    px = jnp.clip((x1[:, None] + rw[:, None]
                   * (jnp.arange(pw)[None, :] + 0.5)).astype(jnp.int32),
                  0, w - 1)
    fg = feat.reshape(oc, ph, pw, h, w)

    def per_roi(pyr, pxr):
        # pyr: PH indices, pxr: PW indices -> OC,PH,PW
        return fg[:, jnp.arange(ph)[:, None], jnp.arange(pw)[None, :],
                  pyr[:, None], pxr[None, :]]

    return jax.vmap(per_roi)(py, px)


@register_op("optimization_barrier", differentiable=False)
def optimization_barrier(ctx):
    """Identity that XLA may not CSE/hoist across. Emitted by the
    recompute planner (backward.py _emit_recompute) at segment
    boundaries so rematerialized clones are not merged back into the
    forward subgraph -- the same mechanism jax.remat relies on."""
    return {"Out": jax.lax.optimization_barrier(ctx.input("X"))}
