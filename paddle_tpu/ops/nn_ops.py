"""Neural-net ops: conv/pool/norm/softmax/dropout/embedding/losses/metrics.

Parity targets: reference paddle/fluid/operators/conv_op.cc (+cuDNN
conv_cudnn_op.cu.cc), pool_op.cc, batch_norm_op.cc/.cu, layer_norm_op.cu,
group_norm_op.cc, softmax_op.cc, softmax_with_cross_entropy_op.cu,
cross_entropy_op.cc, dropout_op.cc, lookup_table_op.cc, lrn_op.cc,
metrics/accuracy_op.cc, auc_op.cc. TPU-first notes:

* conv2d lowers to lax.conv_general_dilated -- XLA tiles it onto the MXU
  (the cuDNN algo-search cache of the reference is obsolete here).
* batch_norm keeps the reference's mutable running-stat semantics by
  emitting MeanOut/VarianceOut as functional state (the executor threads
  them back into the scope).
* dropout SAVES its mask as an output (like the reference) so the grad op
  is deterministic -- the generic vjp grad would re-toss the coin.
* lookup_table's sparse SelectedRows grad path becomes a dense
  scatter-add here; a row-sharded embedding (pserver parity) lives in
  parallel/embedding.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.program import Operator, grad_var_name
from ..core.registry import (OpContext, register_op, get_op_info,
                             EMPTY_VAR)


# --------------------------------------------------------------------------
# conv / pool
# --------------------------------------------------------------------------
def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v]


@register_op("conv2d")
def conv2d(ctx):
    x = ctx.input("Input")
    w = ctx.input("Filter")  # [out_c, in_c/groups, kh, kw]
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    dilations = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1)
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return {"Output": out}


@register_op("depthwise_conv2d")
def depthwise_conv2d(ctx):
    return conv2d(ctx)


@register_op("conv2d_transpose")
def conv2d_transpose(ctx):
    x = ctx.input("Input")
    w = ctx.input("Filter")  # [in_c, out_c/groups, kh, kw]
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    dilations = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1)
    out = _conv_transpose_nd(x, w, strides, pads, dilations, groups,
                             spatial=2)
    return {"Output": out}


def _conv_transpose_nd(x, w, strides, pads, dilations, groups, spatial):
    """Transpose conv as an input-dilated forward conv (the textbook
    identity), matching conv_transpose_op.cc's output formula
    out = (in-1)*s - 2p + d*(k-1) + 1.

    fluid filter layout is [C_in, C_out/g, *k]; the equivalent forward
    kernel is the spatially-flipped, per-group channel-swapped
    [C_out, C_in/g, *k]."""
    ksp = w.shape[2:2 + spatial]
    c_in = x.shape[1]
    c_out_per_g = w.shape[1]
    sp_axes = tuple(range(2, 2 + spatial))
    w_f = jnp.flip(w, axis=sp_axes)
    # [C_in, C_out/g, *k] -> [g, C_in/g, C_out/g, *k] -> swap ->
    # [C_out, C_in/g, *k]
    w_k = w_f.reshape((groups, c_in // groups, c_out_per_g) + ksp)
    w_k = jnp.swapaxes(w_k, 1, 2).reshape(
        (groups * c_out_per_g, c_in // groups) + ksp)
    tpads = [(dilations[i] * (ksp[i] - 1) - pads[i],) * 2
             for i in range(spatial)]
    dn = (("NCHW", "OIHW", "NCHW") if spatial == 2
          else ("NCDHW", "OIDHW", "NCDHW"))
    return jax.lax.conv_general_dilated(
        x, w_k, window_strides=(1,) * spatial, padding=tpads,
        lhs_dilation=tuple(strides), rhs_dilation=tuple(dilations),
        dimension_numbers=dn, feature_group_count=groups)


def _deform_bilinear(img, y, x):
    """Bilinear sample with zero padding outside the image.

    img: [B, G, Cg, H, W]; y/x: [B, G, N] float sample coords in image
    space. Returns [B, G, N, Cg]. One flat gather per corner — the
    whole thing stays a dense static-shape XLA program (no
    data-dependent shapes), so it fuses and vectorizes on TPU.
    """
    B, G, Cg, H, W = img.shape
    flat = img.reshape(B, G, Cg, H * W)
    y0, x0 = jnp.floor(y), jnp.floor(x)
    out = jnp.zeros(y.shape + (Cg,), img.dtype)
    for dy in (0.0, 1.0):
        for dx in (0.0, 1.0):
            yi, xi = y0 + dy, x0 + dx
            w = (1.0 - jnp.abs(y - yi)) * (1.0 - jnp.abs(x - xi))
            valid = ((yi >= 0) & (yi <= H - 1) &
                     (xi >= 0) & (xi <= W - 1))
            idx = (jnp.clip(yi, 0, H - 1) * W +
                   jnp.clip(xi, 0, W - 1)).astype(jnp.int32)
            # flat [B,G,Cg,HW], idx [B,G,N] -> [B,G,Cg,N]
            g = jnp.take_along_axis(flat, idx[:, :, None, :], axis=3)
            g = jnp.moveaxis(g, 2, 3)  # [B,G,N,Cg]
            out = out + jnp.where(valid, w, 0.0)[..., None] * g
    return out


def _deformable_conv_infer_shape(op, block):
    """Output = [B(Input), F(Filter), Ho, Wo(Offset)]. A custom shape
    fn (not the generic eval_shape probe): a -1-batch Input combined
    with a concrete-batch Offset makes the probe's substitute batches
    disagree inside the kernel."""
    x = block._find_var_recursive(op.inputs["Input"][0])
    w = block._find_var_recursive(op.inputs["Filter"][0])
    off = block._find_var_recursive(op.inputs["Offset"][0])
    out = block._find_var_recursive(op.outputs["Output"][0])
    if None in (x, w, off, out) or not (x.shape and w.shape
                                        and off.shape):
        return
    out.shape = (x.shape[0], w.shape[0], off.shape[2], off.shape[3])
    out.dtype = x.dtype


@register_op("deformable_conv", infer_shape=_deformable_conv_infer_shape)
def deformable_conv(ctx):
    """Deformable convolution v1/v2 (Dai et al. '17 / Zhu et al. '19).
    No counterpart op exists in this reference tree (beyond-reference
    capability; the layer name is part of later fluid API surfaces).

    TPU design: instead of the CUDA deformable-im2col kernel, sample
    all B*G*K*Ho*Wo tap positions with one vectorized bilinear gather
    (`_deform_bilinear`), then contract taps x in-channels against the
    filter with a single einsum — the contraction is the FLOPs and XLA
    tiles it onto the MXU. Offset layout matches torchvision/paddle:
    [B, 2*dg*kh*kw, Ho, Wo] with (dy, dx) pairs per tap; optional Mask
    [B, dg*kh*kw, Ho, Wo] gives the modulated (v2) form. Grads come
    from the generic vjp maker (bilinear weights are differentiable in
    the offsets)."""
    x = ctx.input("Input")
    offset = ctx.input("Offset")
    w = ctx.input("Filter")  # [F, C/groups, kh, kw]
    mask = ctx.input("Mask") if ctx.has_input("Mask") else None
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    dilations = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1)
    dg = ctx.attr("deformable_groups", 1)

    B, C, H, W = x.shape
    F, _, kh, kw = w.shape
    K = kh * kw
    Ho = (H + 2 * pads[0] - (dilations[0] * (kh - 1) + 1)) // strides[0] + 1
    Wo = (W + 2 * pads[1] - (dilations[1] * (kw - 1) + 1)) // strides[1] + 1

    # base tap coords (unpadded image space): [K, Ho, Wo]
    ho = jnp.arange(Ho) * strides[0] - pads[0]
    wo = jnp.arange(Wo) * strides[1] - pads[1]
    ki = jnp.arange(kh) * dilations[0]
    kj = jnp.arange(kw) * dilations[1]
    base_y = (ho[None, :] + ki[:, None]).reshape(kh, 1, Ho, 1)
    base_x = (wo[None, :] + kj[:, None]).reshape(1, kw, 1, Wo)
    base_y = jnp.broadcast_to(base_y, (kh, kw, Ho, Wo)).reshape(K, Ho, Wo)
    base_x = jnp.broadcast_to(base_x, (kh, kw, Ho, Wo)).reshape(K, Ho, Wo)

    # offsets: [B, 2*dg*K, Ho, Wo] -> dy/dx [B, dg, K, Ho, Wo]
    off = offset.reshape(B, dg, K, 2, Ho, Wo)
    y = base_y[None, None] + off[:, :, :, 0]
    xx = base_x[None, None] + off[:, :, :, 1]

    img = x.reshape(B, dg, C // dg, H, W)
    samp = _deform_bilinear(img, y.reshape(B, dg, K * Ho * Wo),
                            xx.reshape(B, dg, K * Ho * Wo))
    samp = samp.reshape(B, dg, K, Ho, Wo, C // dg)
    if mask is not None:
        m = mask.reshape(B, dg, K, Ho, Wo)
        samp = samp * m[..., None]
    # [B, dg, K, Ho, Wo, C/dg] -> [B, K, Ho, Wo, C] (dg-major channels)
    samp = jnp.moveaxis(samp, 1, 4).reshape(B, K, Ho, Wo, C)

    # grouped contraction: out[b,g,f,ho,wo] = sum_{c,k} samp * w
    samp_g = samp.reshape(B, K, Ho, Wo, groups, C // groups)
    w_g = w.reshape(groups, F // groups, C // groups, K)
    out = jnp.einsum("bkhwgc,gfck->bghwf", samp_g, w_g,
                     preferred_element_type=samp_g.dtype)
    out = jnp.moveaxis(out, 4, 2).reshape(B, F, Ho, Wo)
    return {"Output": out}


@register_op("switch_moe")
def switch_moe(ctx):
    """Switch/GShard mixture-of-experts FFN block (beyond-reference
    capability; see parallel/moe.py for the routing math and the
    expert-parallel dataflow). Inside a `with expert_parallel(mesh):`
    scope and when token/expert counts divide the ep axis, lowers to
    the shard_map all_to_all form; otherwise runs the identical dense
    math on one device — ep=N and ep=1 are numerically interchangeable
    in the no-drop capacity regime (per-shard FIFO capacity can drop
    different tokens when over-subscribed)."""
    from ..parallel import moe as moe_mod

    x = ctx.input("X")            # [..., D]
    wg = ctx.input("GateW")       # [D, E]
    w1 = ctx.input("W1")          # [E, D, F]
    w2 = ctx.input("W2")          # [E, F, D]
    top_k = int(ctx.attr("top_k", 1))
    cf = float(ctx.attr("capacity_factor", 2.0))
    shape = x.shape
    d = shape[-1]
    xt = x.reshape(-1, d)
    t, E = xt.shape[0], w1.shape[0]
    if moe_mod.ep_applicable(t, E):
        mesh, axis = moe_mod.active_expert_parallel()
        out, aux, drop = moe_mod.moe_apply(
            xt, wg, w1, w2, mesh, axis=axis,
            capacity_factor=cf, top_k=top_k)
    else:
        cap = max(1, int(cf * top_k * t / E))
        out, aux, drop = moe_mod.moe_dense(xt, wg, w1, w2, cap, top_k)
    # DropFrac: fraction of tokens with zero dispatch slots — the
    # first thing to monitor in real MoE training. Extra outputs are
    # free when unfetched (XLA dead-codes them); stop_gradient keeps
    # the monitoring path out of AD.
    return {"Out": out.reshape(shape),
            "AuxLoss": aux.reshape(1).astype(jnp.float32),
            "DropFrac": jax.lax.stop_gradient(drop).reshape(1).astype(
                jnp.float32)}


@register_op("conv3d")
def conv3d(ctx):
    x = ctx.input("Input")
    w = ctx.input("Filter")
    strides = ctx.attr("strides", [1, 1, 1])
    pads = ctx.attr("paddings", [0, 0, 0])
    dilations = ctx.attr("dilations", [1, 1, 1])
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=list(strides),
        padding=[(p, p) for p in pads],
        rhs_dilation=list(dilations),
        feature_group_count=ctx.attr("groups", 1),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    return {"Output": out}


def _pool2d_impl(ctx):
    x = ctx.input("X")
    ptype = ctx.attr("pooling_type", "max")
    ksize = _pair(ctx.attr("ksize", [2, 2]))
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    if ctx.attr("global_pooling", False):
        ksize = [x.shape[2], x.shape[3]]
        pads = [0, 0]
        strides = [1, 1]
    window = (1, 1, ksize[0], ksize[1])
    strides_ = (1, 1, strides[0], strides[1])
    padding = ((0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1]))
    if ptype == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides_,
                                    padding)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_,
                                  padding)
        if ctx.attr("exclusive", True) and (pads[0] or pads[1]):
            ones = jnp.ones_like(x)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides_, padding)
            out = s / cnt
        else:
            out = s / (ksize[0] * ksize[1])
    return out


@register_op("pool2d")
def pool2d(ctx):
    return _pool2d_impl(ctx)


@register_op("adaptive_pool2d")
def adaptive_pool2d(ctx):
    x = ctx.input("X")
    out_hw = ctx.attr("pooling_size", [1, 1])
    ptype = ctx.attr("pooling_type", "avg")
    n, c, h, w = x.shape
    oh, ow = out_hw
    x5 = x.reshape(n, c, oh, h // oh, ow, w // ow)
    if ptype == "avg":
        return x5.mean(axis=(3, 5))
    return x5.max(axis=(3, 5))


# --------------------------------------------------------------------------
# normalization
# --------------------------------------------------------------------------
def _bn_grad_maker(op, no_grad_set=frozenset()):
    """batch_norm grad: differentiate only w.r.t. X/Scale/Bias using saved
    batch statistics; running stats are state, not differentiable."""
    grad_type = "batch_norm_grad"
    from ..core.registry import is_registered, register_op as _reg

    if not is_registered(grad_type):
        _reg(grad_type, differentiable=False)(_bn_grad_kernel)
    inputs = {
        "X": op.inputs["X"], "Scale": op.inputs["Scale"],
        "Bias": op.inputs["Bias"],
        "SavedMean": op.outputs.get("SavedMean", []),
        "SavedVariance": op.outputs.get("SavedVariance", []),
        "Y@GRAD": [grad_var_name(n) for n in op.outputs["Y"]],
    }
    outputs = {}
    for slot in ("X", "Scale", "Bias"):
        names = op.inputs[slot]
        if all(n in no_grad_set for n in names):
            continue
        outputs[slot + "@GRAD"] = [grad_var_name(n) for n in names]
    attrs = dict(op.attrs)
    return [Operator(op.block, grad_type, inputs, outputs, attrs)]


def _bn_grad_kernel(ctx):
    x = ctx.input("X")
    scale = ctx.input("Scale")
    mean = ctx.input("SavedMean")
    inv_std = ctx.input("SavedVariance")  # we save inv-std like cuDNN
    dy = ctx.input("Y@GRAD")
    eps = ctx.attr("epsilon", 1e-5)
    layout = ctx.attr("data_layout", "NCHW")
    axes = (0, 2, 3) if (layout == "NCHW" and x.ndim == 4) else \
        tuple(i for i in range(x.ndim) if i != x.ndim - 1)
    shape = [1] * x.ndim
    caxis = 1 if (layout == "NCHW" and x.ndim == 4) else x.ndim - 1
    shape[caxis] = x.shape[caxis]
    m = float(np.prod([x.shape[a] for a in axes]))
    mean_b = mean.reshape(shape)
    inv_b = inv_std.reshape(shape)
    xhat = (x - mean_b) * inv_b
    dbias = jnp.sum(dy, axis=axes)
    dscale = jnp.sum(dy * xhat, axis=axes)
    if ctx.attr("is_test", False) or ctx.attr(
            "use_global_stats", False):
        dx = dy * scale.reshape(shape) * inv_b
    else:
        dx = (scale.reshape(shape) * inv_b / m) * (
            m * dy - dbias.reshape(shape)
            - xhat * dscale.reshape(shape))
    out = {"X@GRAD": dx, "Scale@GRAD": dscale, "Bias@GRAD": dbias}
    return {k: v for k, v in out.items() if k in
            {s for s in ctx.op.outputs}}


@register_op("batch_norm", grad_maker=_bn_grad_maker)
def batch_norm(ctx):
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    mean_in, var_in = ctx.input("Mean"), ctx.input("Variance")
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    is_test = ctx.attr("is_test", False) or ctx.attr(
        "use_global_stats", False)
    layout = ctx.attr("data_layout", "NCHW")
    if layout == "NCHW" and x.ndim == 4:
        axes, caxis = (0, 2, 3), 1
    else:
        axes, caxis = tuple(i for i in range(x.ndim - 1)), x.ndim - 1
    shape = [1] * x.ndim
    shape[caxis] = x.shape[caxis]
    if is_test:
        mean, var = mean_in, var_in
        y = (x - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + eps) * scale.reshape(shape) \
            + bias.reshape(shape)
        return {"Y": y, "MeanOut": mean_in, "VarianceOut": var_in,
                "SavedMean": mean_in,
                "SavedVariance": jax.lax.rsqrt(var_in + eps)}
    mean = jnp.mean(x, axis=axes)
    var = jnp.mean(jnp.square(x), axis=axes) - jnp.square(mean)
    inv_std = jax.lax.rsqrt(var + eps)
    y = (x - mean.reshape(shape)) * inv_std.reshape(shape) \
        * scale.reshape(shape) + bias.reshape(shape)
    mean_out = mean_in * momentum + mean * (1 - momentum)
    var_out = var_in * momentum + var * (1 - momentum)
    return {"Y": y, "MeanOut": mean_out, "VarianceOut": var_out,
            "SavedMean": mean, "SavedVariance": inv_std}


@register_op("layer_norm")
def layer_norm(ctx):
    """Statistics always run in fp32 regardless of input dtype (the
    pallas kernel already did; the jnp fallback now matches). NOTE the
    op stays on the AMP BLACK list: keeping LN bf16-in/bf16-out to
    elide the convert chain was tried and measured SLOWER on v5e
    (200.6 vs 184 ms/step transformer-base) -- XLA folds the converts
    into neighboring fusions for free, while bf16 IO degrades the
    pallas LN tiles. See PERF.md dead ends."""
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 1e-5)
    begin = ctx.attr("begin_norm_axis", 1)
    lead = int(np.prod(x.shape[:begin]))
    x2 = x.reshape(lead, -1)
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    x2f = x2.astype(jnp.float32)
    mean = jnp.mean(x2f, axis=1, keepdims=True)
    var = jnp.var(x2f, axis=1, keepdims=True)
    from .pallas import layer_norm as pallas_ln

    if scale is not None and bias is not None:
        s1, b1 = scale.reshape(-1), bias.reshape(-1)
        # pallas kernel when usable, else its oracle (_ln_ref) -- ONE
        # fp32 recipe shared with the kernel's custom_vjp backward
        y = (pallas_ln.layer_norm(x2, s1, b1, eps)
             if pallas_ln.usable(lead, x2.shape[1])
             else pallas_ln._ln_ref(x2, s1, b1, eps))
        return {"Y": y.reshape(x.shape), "Mean": mean.reshape(lead),
                "Variance": var.reshape(lead)}
    y = (x2f - mean) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale.reshape(1, -1).astype(jnp.float32)
    if bias is not None:
        y = y + bias.reshape(1, -1).astype(jnp.float32)
    return {"Y": y.astype(x.dtype).reshape(x.shape),
            "Mean": mean.reshape(lead),
            "Variance": var.reshape(lead)}


@register_op("group_norm")
def group_norm(ctx):
    x = ctx.input("X")  # NCHW
    g = ctx.attr("groups")
    eps = ctx.attr("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape(n, g, -1)
    mean = jnp.mean(xg, axis=2, keepdims=True)
    var = jnp.var(xg, axis=2, keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    shape = [1, c] + [1] * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return {"Y": y, "Mean": mean.reshape(n, g), "Variance": var.reshape(n, g)}


@register_op("instance_norm")
def instance_norm(ctx):
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return {"Y": y, "SavedMean": mean.reshape(x.shape[0], x.shape[1]),
            "SavedVariance": var.reshape(x.shape[0], x.shape[1])}


@register_op("lrn")
def lrn(ctx):
    x = ctx.input("X")  # NCHW
    n_size = ctx.attr("n", 5)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    k = ctx.attr("k", 1.0)
    sq = jnp.square(x)
    half = n_size // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n_size))
    mid = (k + alpha * acc) ** beta
    return {"Out": x / mid, "MidOut": mid}


@register_op("l2_normalize")
def l2_normalize(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    eps = ctx.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": x / norm, "Norm": norm}


@register_op("norm")
def norm_op(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    eps = ctx.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": x / norm, "Norm": norm}


# --------------------------------------------------------------------------
# softmax & losses
# --------------------------------------------------------------------------
@register_op("softmax")
def softmax(ctx):
    return jax.nn.softmax(ctx.input("X"), axis=ctx.attr("axis", -1))


@register_op("log_softmax")
def log_softmax(ctx):
    return jax.nn.log_softmax(ctx.input("X"), axis=ctx.attr("axis", -1))


def _swce_grad_maker(op, no_grad_set=frozenset()):
    """Fused grad recomputed from saved Logits (reference
    softmax_with_cross_entropy_op.cu backward keeps the softmax tensor;
    recomputing it from logits trades cheap VPU FLOPs for the [N,V]
    probability buffer -- with a 32k vocab that buffer dominates HBM, so
    this is the TPU-right choice and lets XLA dead-code the unfetched
    Softmax output entirely)."""
    from ..core.registry import is_registered, register_op as _reg

    if not is_registered("softmax_with_cross_entropy_grad"):
        _reg("softmax_with_cross_entropy_grad", differentiable=False)(
            _swce_grad_kernel)
    inputs = {
        "Logits": op.inputs["Logits"],
        "Label": op.inputs["Label"],
        "Loss@GRAD": [grad_var_name(n) for n in op.outputs["Loss"]],
    }
    outputs = {"Logits@GRAD": [grad_var_name(n)
                               for n in op.inputs["Logits"]]}
    return [Operator(op.block, "softmax_with_cross_entropy_grad", inputs,
                     outputs, dict(op.attrs))]


def _swce_grad_kernel(ctx):
    """grad = (softmax - target) * dloss, emitted directly in the
    logits' storage dtype: the fp32 probabilities exist only inside
    the fused exp(l - lse) expression, never as an [N, V] HBM buffer;
    the hard-label one-hot subtraction is a fused iota==label compare
    select, not a materialized one-hot."""
    logits = ctx.input("Logits")
    label = ctx.input("Label")
    dloss = ctx.input("Loss@GRAD")
    if dloss is None:
        dloss = jnp.ones(logits.shape[:-1] + (1,), jnp.float32)
    dloss = dloss.astype(jnp.float32)
    eps = ctx.attr("label_smooth_eps", 0.0)
    vocab = logits.shape[-1]
    if not ctx.attr("soft_label", False):
        from .pallas import xent as pallas_xent

        routed = pallas_xent.maybe_route(logits, label)
        if routed is not None:
            l2, lab1 = routed
            dx = pallas_xent.xent_backward(
                l2, lab1, dloss.reshape(-1), eps=eps,
                ignore_index=ctx.attr("ignore_index", -100))
            return {"Logits@GRAD": dx.reshape(logits.shape)}
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1, keepdims=True)
    p_scaled = jnp.exp(lf - lse) * dloss  # fused, lands in grad
    if ctx.attr("soft_label", False):
        target = label.astype(jnp.float32)
        if eps:
            target = target * (1.0 - eps) + eps / vocab
        grad = p_scaled - target * dloss
        return {"Logits@GRAD": grad.astype(logits.dtype)}
    lab = label.astype(jnp.int32)
    if lab.ndim == logits.ndim:
        lab = lab[..., 0]
    ignore = ctx.attr("ignore_index", -100)
    valid = (lab != ignore)[..., None]
    dloss = jnp.where(valid, dloss, 0.0)
    p_scaled = jnp.where(valid, p_scaled, 0.0)
    if eps:
        grad = p_scaled - (eps / vocab) * dloss
        hit = (1.0 - eps) * dloss
    else:
        grad = p_scaled
        hit = dloss
    # one-hot as a fused iota==label compare: elementwise over [N,V],
    # no scatter temp, no materialized one-hot -- the whole expression
    # collapses into the single bf16 output pass
    iota = jnp.arange(vocab, dtype=jnp.int32)
    onehot = (iota == lab[..., None])
    grad = grad - jnp.where(onehot, hit, 0.0)
    return {"Logits@GRAD": grad.astype(logits.dtype)}


@register_op("softmax_with_cross_entropy", grad_maker=_swce_grad_maker)
def softmax_with_cross_entropy(ctx):
    """Reduction-form xent: loss = lse(logits) - logits[label].

    With a 32k vocab the [N, V] tensors dominate HBM traffic, so the
    kernel never materializes an fp32 log-softmax: logits stay in
    their storage dtype (bf16 under AMP -- this op is on the amp KEEP
    list and manages its own precision), the logsumexp reduction
    accumulates in fp32 on the fly, and the label logit is a gather.
    The Softmax output is only computed when a consumer fetches it
    (XLA dead-codes it otherwise)."""
    logits = ctx.input("Logits")
    label = ctx.input("Label")
    eps = ctx.attr("label_smooth_eps", 0.0)
    if not ctx.attr("soft_label", False):
        from .pallas import xent as pallas_xent

        routed = pallas_xent.maybe_route(logits, label)
        if routed is not None:
            l2, lab1 = routed
            loss_flat, lse_flat = pallas_xent.xent_forward(
                l2, lab1, eps=eps,
                ignore_index=ctx.attr("ignore_index", -100))
            loss = loss_flat.reshape(logits.shape[:-1] + (1,))
            # Softmax output stays a jnp expression off the pallas lse:
            # XLA dead-codes it when (as in every model here) nothing
            # consumes the Softmax slot
            sm = jnp.exp(logits.astype(jnp.float32)
                         - lse_flat.reshape(
                             logits.shape[:-1] + (1,)))
            return {"Loss": loss, "Softmax": sm}
    lf = logits.astype(jnp.float32)  # fuses into the reductions below
    lse = jax.scipy.special.logsumexp(lf, axis=-1, keepdims=True)
    if ctx.attr("soft_label", False):
        # sum(label * (lse - logits)) = lse - sum(label * logits)
        loss = lse - jnp.sum(label.astype(jnp.float32) * lf, axis=-1,
                             keepdims=True)
        if eps:
            uniform = lse - jnp.mean(lf, axis=-1, keepdims=True)
            loss = (1.0 - eps) * loss + eps * uniform
    else:
        lab = label.astype(jnp.int32)
        if lab.ndim == logits.ndim:
            lab = lab[..., 0]
        ignore = ctx.attr("ignore_index", -100)
        valid = lab != ignore
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(lf, safe[..., None], axis=-1)
        loss = lse - picked
        if eps:
            # smoothed target (1-eps)*onehot + eps/V without the [N,V]
            # one-hot: mean_j(lse - logits_j) = lse - mean(logits)
            uniform = lse - jnp.mean(lf, axis=-1, keepdims=True)
            loss = (1.0 - eps) * loss + eps * uniform
        loss = jnp.where(valid[..., None], loss, 0.0)
    sm = jnp.exp(lf - lse)
    return {"Loss": loss, "Softmax": sm}


@register_op("cross_entropy", stop_gradient_slots=("Label",))
def cross_entropy(ctx):
    x = ctx.input("X")  # probabilities
    label = ctx.input("Label")
    if ctx.attr("soft_label", False):
        return -jnp.sum(label * jnp.log(x + 1e-20), axis=-1, keepdims=True)
    lab = label.astype(jnp.int32)
    if lab.ndim == x.ndim:
        lab = lab[..., 0]
    p = jnp.take_along_axis(x, lab[..., None], axis=-1)
    return -jnp.log(p + 1e-20)


@register_op("sigmoid_cross_entropy_with_logits",
             stop_gradient_slots=("Label",))
def sigmoid_ce_logits(ctx):
    x = ctx.input("X")
    label = ctx.input("Label")
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = ctx.attr("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    if ctx.attr("normalize", False):
        n = jnp.maximum(jnp.sum(label != ignore).astype(x.dtype), 1.0)
        loss = loss / n
    return loss


@register_op("square_error_cost")
def square_error_cost(ctx):
    d = ctx.input("X") - ctx.input("Y")
    return d * d


@register_op("huber_loss")
def huber_loss(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    delta = ctx.attr("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    quad = 0.5 * r * r
    lin = delta * (a - 0.5 * delta)
    loss = jnp.where(a <= delta, quad, lin)
    return {"Out": loss, "Residual": r}


@register_op("log_loss")
def log_loss(ctx):
    p = ctx.input("Predicted")
    y = ctx.input("Labels")
    eps = ctx.attr("epsilon", 1e-4)
    return -y * jnp.log(p + eps) - (1 - y) * jnp.log(1 - p + eps)


@register_op("smooth_l1_loss")
def smooth_l1_loss(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    sigma = ctx.attr("sigma", 1.0)
    s2 = sigma * sigma
    d = x - y
    iw = ctx.input("InsideWeight")
    if iw is not None:
        d = d * iw
    a = jnp.abs(d)
    loss = jnp.where(a < 1.0 / s2, 0.5 * d * d * s2, a - 0.5 / s2)
    ow = ctx.input("OutsideWeight")
    if ow is not None:
        loss = loss * ow
    red = loss.reshape(loss.shape[0], -1).sum(axis=1, keepdims=True)
    return {"Out": red, "Diff": d}


@register_op("hinge_loss")
def hinge_loss(ctx):
    logits = ctx.input("Logits")
    labels = ctx.input("Labels")
    return jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)


@register_op("margin_rank_loss")
def margin_rank_loss(ctx):
    x1, x2 = ctx.input("X1"), ctx.input("X2")
    label = ctx.input("Label")
    margin = ctx.attr("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("bpr_loss", stop_gradient_slots=("Label",))
def bpr_loss(ctx):
    x = ctx.input("X")
    label = ctx.input("Label").astype(jnp.int32)
    if label.ndim == x.ndim:
        label = label[..., 0]
    pos = jnp.take_along_axis(x, label[..., None], axis=-1)
    diff = x - pos
    loss = jnp.log1p(jnp.exp(diff))
    n = x.shape[-1]
    mask = 1.0 - jax.nn.one_hot(label, n, dtype=x.dtype)
    return jnp.sum(loss * mask, axis=-1, keepdims=True) / (n - 1)


@register_op("kldiv_loss", stop_gradient_slots=("Target",))
def kldiv_loss(ctx):
    x = ctx.input("X")  # log-probabilities
    t = ctx.input("Target")
    loss = t * (jnp.log(jnp.maximum(t, 1e-20)) - x)
    red = ctx.attr("reduction", "mean")
    if red == "mean":
        return jnp.mean(loss).reshape(1)
    if red == "sum":
        return jnp.sum(loss).reshape(1)
    if red == "batchmean":
        return (jnp.sum(loss) / x.shape[0]).reshape(1)
    return loss


# --------------------------------------------------------------------------
# dropout (mask saved for deterministic grad, reference dropout_op.cc)
# --------------------------------------------------------------------------
def _dropout_grad_maker(op, no_grad_set=frozenset()):
    from ..core.registry import is_registered, register_op as _reg

    if not is_registered("dropout_grad"):
        _reg("dropout_grad", differentiable=False)(_dropout_grad_kernel)
    inputs = {"Mask": op.outputs["Mask"],
              "Out@GRAD": [grad_var_name(n) for n in op.outputs["Out"]]}
    outputs = {"X@GRAD": [grad_var_name(n) for n in op.inputs["X"]]}
    return [Operator(op.block, "dropout_grad", inputs, outputs,
                     dict(op.attrs))]


def _dropout_grad_kernel(ctx):
    dy = ctx.input("Out@GRAD")
    mask = ctx.input("Mask")
    p = ctx.attr("dropout_prob", 0.5)
    impl = ctx.attr("dropout_implementation", "downgrade_in_infer")
    if ctx.attr("is_test", False):
        if impl == "upscale_in_train":
            return {"X@GRAD": dy}
        return {"X@GRAD": dy * (1.0 - p)}
    if impl == "upscale_in_train":
        scale = 1.0 / max(1.0 - p, 1e-8)
        return {"X@GRAD": dy * mask * scale}
    return {"X@GRAD": dy * mask}


@register_op("dropout", grad_maker=_dropout_grad_maker, needs_rng=True)
def dropout(ctx):
    x = ctx.input("X")
    p = ctx.attr("dropout_prob", 0.5)
    is_test = ctx.attr("is_test", False)
    impl = ctx.attr("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = x if impl == "upscale_in_train" else x * (1.0 - p)
        return {"Out": out, "Mask": jnp.ones_like(x)}
    seed = ctx.attr("seed", 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.rng()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape).astype(x.dtype)
    if impl == "upscale_in_train":
        out = x * keep / max(1.0 - p, 1e-8)
    else:
        out = x * keep
    return {"Out": out, "Mask": keep}


# --------------------------------------------------------------------------
# embedding (reference lookup_table_op.cc; SelectedRows grad -> scatter-add)
# --------------------------------------------------------------------------
def _lookup_grad_maker(op, no_grad_set=frozenset()):
    from ..core.registry import is_registered, register_op as _reg

    if not is_registered("lookup_table_grad"):
        _reg("lookup_table_grad", differentiable=False)(
            _lookup_grad_kernel)
    inputs = {"W": op.inputs["W"], "Ids": op.inputs["Ids"],
              "Out@GRAD": [grad_var_name(n) for n in op.outputs["Out"]]}
    w = op.inputs["W"][0]
    if w in no_grad_set:
        return []
    outputs = {"W@GRAD": [grad_var_name(w)]}
    return [Operator(op.block, "lookup_table_grad", inputs, outputs,
                     dict(op.attrs))]


def _lookup_grad_kernel(ctx):
    w = ctx.input("W")
    ids = ctx.input("Ids").astype(jnp.int32)
    dy = ctx.input("Out@GRAD")
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    flat_ids = ids.reshape(-1)
    flat_dy = dy.reshape(-1, w.shape[-1])
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        keep = (flat_ids != padding_idx).astype(flat_dy.dtype)
        flat_dy = flat_dy * keep[:, None]
    dw = jnp.zeros_like(w).at[flat_ids].add(flat_dy)
    return {"W@GRAD": dw}


@register_op("lookup_table", grad_maker=_lookup_grad_maker,
             stop_gradient_slots=("Ids",))
def lookup_table(ctx):
    w = ctx.input("W")
    ids = ctx.input("Ids").astype(jnp.int32)
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids[..., 0]
    out = jnp.take(w, ids, axis=0)
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx).astype(w.dtype)[..., None]
        out = out * mask
    return out


@register_op("lookup_table_v2", grad_maker=_lookup_grad_maker,
             stop_gradient_slots=("Ids",))
def lookup_table_v2(ctx):
    return lookup_table(ctx)


@register_op("embedding_grad_dense_to_sparse", differentiable=False)
def embedding_grad_dense_to_sparse(ctx):
    # capability surface for SelectedRows-style sparse grads: returns the
    # unique rows + their grads (reference selected_rows.h:32 analogue)
    return ctx.input("X")


# --------------------------------------------------------------------------
# metrics (reference metrics/accuracy_op.cc, auc_op.cc)
# --------------------------------------------------------------------------
@register_op("accuracy", differentiable=False)
def accuracy(ctx):
    indices = ctx.input("Indices")
    label = ctx.input("Label").astype(indices.dtype)
    if label.ndim == 1:
        label = label[:, None]
    correct = jnp.any(indices == label, axis=-1)
    total = correct.shape[0]
    num_correct = jnp.sum(correct.astype(jnp.float32))
    acc = (num_correct / total).reshape(1)
    return {"Accuracy": acc,
            "Correct": num_correct.astype(jnp.int32).reshape(1),
            "Total": jnp.array([total], dtype=jnp.int32)}


@register_op("auc", differentiable=False)
def auc(ctx):
    """Streaming AUC via histogram buckets (reference auc_op.cc)."""
    preds = ctx.input("Predict")
    label = ctx.input("Label").reshape(-1)
    stat_pos = ctx.input("StatPos")
    stat_neg = ctx.input("StatNeg")
    num_thresholds = ctx.attr("num_thresholds", 4095)
    pos_prob = preds[:, -1] if preds.ndim == 2 else preds.reshape(-1)
    bucket = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                      num_thresholds)
    is_pos = (label > 0).astype(stat_pos.dtype)
    new_pos = stat_pos.at[bucket].add(is_pos)
    new_neg = stat_neg.at[bucket].add(1 - is_pos)
    # compute AUC from histograms (trapezoid over thresholds)
    tot_pos = jnp.cumsum(new_pos[::-1])[::-1]
    tot_neg = jnp.cumsum(new_neg[::-1])[::-1]
    tp = tot_pos
    fp = tot_neg
    p_total = jnp.maximum(tp[0], 1)
    n_total = jnp.maximum(fp[0], 1)
    tpr = tp / p_total
    fpr = fp / n_total
    auc_val = -jnp.trapezoid(tpr, fpr)
    return {"AUC": auc_val.reshape(1).astype(jnp.float32),
            "StatPosOut": new_pos, "StatNegOut": new_neg}


@register_op("mean_iou", differentiable=False)
def mean_iou(ctx):
    pred = ctx.input("Predictions").reshape(-1).astype(jnp.int32)
    label = ctx.input("Labels").reshape(-1).astype(jnp.int32)
    n = ctx.attr("num_classes")
    inter = jnp.zeros(n).at[jnp.where(pred == label, pred, n - 1)].add(
        (pred == label).astype(jnp.float32))
    pred_cnt = jnp.zeros(n).at[pred].add(1.0)
    lab_cnt = jnp.zeros(n).at[label].add(1.0)
    union = pred_cnt + lab_cnt - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1e-9), 0.0)
    valid = (union > 0).astype(jnp.float32)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1.0)
    return {"OutMeanIou": miou.reshape(1), "OutWrong": union,
            "OutCorrect": inter}


# --------------------------------------------------------------------------
# fused scaled-dot-product attention -- the framework-level attention op.
# Routes to the Pallas flash-attention kernel on TPU for supported shapes
# (ops/pallas/attention.py); falls back to the jnp composition (which XLA
# still fuses well). The reference has no fused attention op -- attention
# exists only as a layer composition (reference nets.py
# scaled_dot_product_attention) -- so this op is a TPU-first upgrade.
# --------------------------------------------------------------------------
@register_op("ffn_block")
def ffn_block_op(ctx):
    """Whole-layer fused position-wise MLP: ONE op for
    relu(x @ W1 + b1) @ W2 + b2 (the MLP half of PERF.md's
    whole-layer-fusion lever; kernel in ops/pallas/ffn_block.py).
    Grads flow through the kernel's custom_vjp (hidden recomputed,
    never stored to HBM)."""
    x = ctx.input("X")
    w1, b1 = ctx.input("W1"), ctx.input("B1")
    w2, b2 = ctx.input("W2"), ctx.input("B2")
    from .pallas import ffn_block as FB

    if FB.usable(x, w1):
        return {"Out": FB.ffn_block(x, w1, b1, w2, b2)}
    return {"Out": FB.ffn_block_reference(x, w1, b1, w2, b2)}


@register_op("attention_block")
def attention_block_op(ctx):
    """Whole-layer fused self-attention sub-layer: ONE op for
    x @ Wqkv -> split-heads SDPA -> merge -> @ Wo (the PERF.md
    whole-layer-fusion lever; kernel in ops/pallas/attention_block.py).
    Replaces the 7-op sequence multi_head_attention otherwise emits;
    grads come from the generic vjp, which flows through the kernel's
    custom_vjp (saved-P backward, zero exps)."""
    x = ctx.input("X")
    wqkv = ctx.input("WQKV")
    wo = ctx.input("WO")
    n_heads = int(ctx.attr("n_heads"))
    scale = ctx.attr("scale", None)
    if scale is None:
        scale = (x.shape[-1] // n_heads) ** -0.5
    causal = ctx.attr("causal", False)
    from .pallas import attention_block as AB

    if AB.usable(x, wqkv, n_heads):
        out = AB.attention_block(x, wqkv, wo, n_heads, float(scale),
                                 bool(causal))
    else:
        out = AB.attention_block_reference(x, wqkv, wo, n_heads,
                                           float(scale), bool(causal))
    return {"Out": out}


@register_op("attention", needs_rng=True)
def attention(ctx):
    """layout attr: 'bhtd' (default) or 'bthd'. The bthd form takes
    q/k/v straight from the head-split reshape WITHOUT a physical
    [B,T,H,D]->[B,H,T,D] transpose -- dot_general batches over h in
    place, which removed ~30ms/step of transpose+copy HLOs from
    transformer-base (profiled on v5e; the transposes and their jvp
    duals were ~15% of device time). The pallas flash kernel keeps its
    bhtd contract, so routes through transposes only when it is
    actually selected (long sequences)."""
    q = ctx.input("Q")
    k = ctx.input("K")
    v = ctx.input("V")
    scale = ctx.attr("scale", None)
    causal = ctx.attr("causal", False)
    layout = ctx.attr("layout", "bhtd")
    dropout_rate = ctx.attr("dropout_rate", 0.0)
    if ctx.attr("is_test", False):
        dropout_rate = 0.0
    if scale is None:
        scale = q.shape[-1] ** -0.5
    from . import pallas
    from .pallas import attention as pallas_attn
    from ..parallel import ring_attention as ra

    def to_bhtd(x):
        return jnp.swapaxes(x, 1, 2) if layout == "bthd" else x

    qh, kh, vh = to_bhtd(q), to_bhtd(k), to_bhtd(v)
    if ra.cp_applicable(qh, kh, vh, dropout_rate):
        return to_bhtd(ra.cp_attention(qh, kh, vh, scale, causal))
    if dropout_rate == 0.0:
        if pallas_attn.sdpa_usable(qh, kh, vh):
            # short-T fused SDPA: scores never touch HBM and the
            # backward reuses the saved probabilities instead of
            # re-exping (the VPU exp rate is the floor at short T --
            # see the kernel's module comment). Worth the bthd
            # transposes at every size it accepts.
            return to_bhtd(pallas_attn.sdpa_short(
                qh, kh, vh, scale=scale, causal=causal))
        if pallas_attn.usable(qh, kh, vh) and qh.shape[2] > 512:
            # flash wins only at long T (its b*h-programs grid is
            # launch-overhead-bound below that -- measured slower than
            # the jnp composition at T<=512 on v5e, either layout)
            return to_bhtd(pallas_attn.flash_attention(
                qh, kh, vh, scale=scale, causal=causal))
        if layout == "bthd":
            return _attention_bthd(q, k, v, scale, causal)
        return pallas.reference_attention(q, k, v, scale, causal)
    # dropout between softmax and the V product forces the inline form
    return to_bhtd(_sdpa(qh, kh, vh, scale, causal, "bhtd",
                         dropout_rate=dropout_rate, rng=ctx.rng()))


def _attention_bthd(q, k, v, scale, causal):
    return _sdpa(q, k, v, scale, causal, "bthd")


def _sdpa(q, k, v, scale, causal, layout, dropout_rate=0.0, rng=None):
    """The one masked-softmax attention body behind both layouts and
    the dropout path (pallas.reference_attention stays a deliberately
    independent oracle for kernel tests). QK^T and PV accumulate in
    fp32 via preferred_element_type -- bf16 inputs stay in HBM, the
    MXU accumulator carries the precision, matching the flash kernel's
    numerics."""
    if layout == "bthd":
        qk, pv = "bqhd,bkhd->bhqk", "bhqk,bkhd->bqhd"
    else:
        qk, pv = "bhqd,bhkd->bhqk", "bhqk,bhkd->bhqd"
    s = jnp.einsum(qk, q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), dtype=bool), tk - tq)
        s = jnp.where(mask, s, jnp.finfo(s.dtype).min)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, p.shape)
        p = p * keep / (1.0 - dropout_rate)
    out = jnp.einsum(pv, p.astype(q.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# --------------------------------------------------------------------------
# fc: fused mul+add+act (reference operators/fc_op-era fc; produced by
# ir.fc_fuse_pass like ir/fc_fuse_pass.cc produces the fc op)
# --------------------------------------------------------------------------
@register_op("fc")
def fc(ctx):
    from .math_ops import _flatten2d

    x = ctx.input("Input")
    w = ctx.input("W")
    b = ctx.input("Bias")
    ncd = ctx.attr("in_num_col_dims", 1)
    x2 = _flatten2d(x, ncd)
    out = jnp.matmul(x2, jnp.reshape(w, (x2.shape[-1], -1)))
    if b is not None:
        out = out + jnp.reshape(b, (1, -1))
    # restore the leading dims the mul op would have kept (mul_op.cc
    # reshapes to x.shape[:ncd] + y.shape[ync:])
    out = jnp.reshape(out, x.shape[:ncd] + (out.shape[-1],))
    act = ctx.attr("activation_type", "")
    if act == "relu":
        out = jax.nn.relu(out)
    elif act == "tanh":
        out = jnp.tanh(out)
    elif act == "softmax":
        out = jax.nn.softmax(out, axis=-1)
    elif act:
        raise ValueError(f"fc: unsupported activation {act!r}")
    return {"Out": out}


@register_op("adaptive_pool3d")
def adaptive_pool3d(ctx):
    """reference operators/pool_op.cc adaptive path, 3-D: NCDHW input
    pooled to pooling_size output cells (divisible case, like
    adaptive_pool2d above)."""
    x = ctx.input("X")
    od, oh, ow = ctx.attr("pooling_size", [1, 1, 1])
    ptype = ctx.attr("pooling_type", "avg")
    n, c, d, h, w = x.shape
    x7 = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
    if ptype == "avg":
        return x7.mean(axis=(3, 5, 7))
    return x7.max(axis=(3, 5, 7))
