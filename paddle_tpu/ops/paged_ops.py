"""Paged-KV pool ops (models/decode_engine.py paged layout).

Reference counterpart: none — the reference framework's decode caches
are per-request dense tensors (reference
tests/unittests/dist_transformer.py:1498 fast_decode caches). The
shared block pool follows vLLM's PagedAttention block tables
(SOSP'23, PAPERS.md), re-designed for XLA static shapes: the pool is
one persistable tensor, lanes address it through host-allocated
int32 tables, reads are plain `gather` composition, and ALL writes
funnel through the single op below so the lane-exclusivity contract
is one auditable surface (analysis checker PTA110).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op


@register_op("masked_pool_write", differentiable=False,
             stop_gradient_slots=("Pool", "New", "Index", "Gate"))
def masked_pool_write(ctx):
    """Disjoint one-hot masked scatter into a SHARED KV pool.

    inputs: Pool [N0(, N1), ...tail] (the pool var — also the op's
    output, an in-place read-modify-write so the var rides the
    executor's state_in path); New [R, ...tail]; Index [R] int
    (flattened leading index of each row's target cell); Gate [R]
    optional 0/1 (rows with gate 0 — idle/dustbin/paused lanes —
    write nothing). attrs: leading_dims (how many leading Pool axes
    the Index addresses, flattened), exclusive_via (the builder's
    declaration of WHY row indices cannot alias: "block_table" =
    per-lane blocks from a host free-list, "host_indices" =
    host-deduplicated admission targets, "cow_dst" = freshly
    allocated exclusive blocks a COW copy diverges a lane into —
    checker PTA110 requires it).

    Out-of-range and gated-off rows write nothing (they scatter into
    a trash row that is sliced away), and cells hit by a gated row
    take EXACTLY the new value. The lowering is an indexed row
    scatter — O(R x cell) instead of the O(n_cells x R x cell)
    one-hot matmul, which MEASURED as ~3x the cost of the attention
    itself per decode tick at small head dims; the semantics are the
    disjoint-one-hot-mask semantics PTA110 assumes (under the
    exclusivity contract the two lowerings are identical — aliased
    gated rows are the corruption class the host allocator + PTA110
    exclude, not something either lowering can repair).

    Since the ownership prover landed, ``exclusive_via`` is more
    than a declaration: the abstract interpreter (analysis/absint.py
    ownership domain) chains the Index input's provenance back to a
    marked host-owned source and PTA191 PROVES lane-exclusivity
    under that source's named allocator assumption — a via that
    disagrees with the proven chain, an index of unknown provenance
    (PTA190), or an index reaching a REFCOUNTED shared entry
    (PTA192 write-while-shared, the COW contract) are build-time
    errors. The trash-row clamp covers out-of-range WRITES; reads
    have no such net, which is why PTA190 also proves gather bounds.
    """
    pool = ctx.input("Pool")
    new = ctx.input("New")
    idx = ctx.input("Index")
    gate = ctx.input("Gate")
    lead = int(ctx.attr("leading_dims", 1))
    n = 1
    for d in pool.shape[:lead]:
        n *= int(d)
    pool_flat = pool.reshape(n, -1)
    rows = new.shape[0]
    new_flat = new.reshape(rows, -1).astype(pool_flat.dtype)
    idx = idx.reshape(rows).astype(jnp.int32)
    keep = (idx >= 0) & (idx < n)
    if gate is not None:
        keep = keep & (gate.reshape(rows) > 0)
    safe = jnp.where(keep, idx, n)  # n = the trash row below
    padded = jnp.concatenate(
        [pool_flat, jnp.zeros((1,) + pool_flat.shape[1:],
                              pool_flat.dtype)], axis=0)
    out = padded.at[safe].set(new_flat,
                              unique_indices=False,
                              indices_are_sorted=False)[:n]
    return out.reshape(pool.shape)
