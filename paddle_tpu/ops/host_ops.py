"""Host-callback ops: py_func, chunk_eval, go.

Parity: reference operators/py_func_op.cc (call back into Python from
a graph op — the custom-op escape hatch), operators/chunk_eval_op.cc
(chunk detection metrics for sequence labeling), operators/csp/go_op.cc
(goroutine-style concurrent block execution).

TPU-native: all three are host effects bridged through
jax.experimental.io_callback / pure_callback from inside the compiled
program — the XLA equivalent of the reference's CPU-only kernels.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

from ..core.program import Operator, grad_var_name
from ..core.registry import register_op
from ..core.types import to_jnp_dtype

# registered python callables for py_func (reference py_func_op.cc
# keeps a static registry the op indexes into)
_PY_FUNC_REGISTRY: List[Callable] = []
_PY_FUNC_IDS: Dict[int, int] = {}


def register_py_func(fn: Callable) -> int:
    """Idempotent per function object: re-registering the same callable
    returns its existing id (keeps PyLayer classes from growing the
    registry once per instance/call)."""
    existing = _PY_FUNC_IDS.get(id(fn))
    if existing is not None and _PY_FUNC_REGISTRY[existing] is fn:
        return existing
    _PY_FUNC_REGISTRY.append(fn)
    _PY_FUNC_IDS[id(fn)] = len(_PY_FUNC_REGISTRY) - 1
    return len(_PY_FUNC_REGISTRY) - 1


def _py_func_grad_maker(op, no_grad_set=frozenset()):
    if op.attr("backward_callable_id", -1) < 0:
        return []
    inputs = {"X": list(op.input("X")),
              "Out": list(op.output("Out")),
              "Out@GRAD": [grad_var_name(n)
                           for n in op.output("Out")]}
    outputs = {"X@GRAD": [grad_var_name(n) for n in op.input("X")
                          if n not in no_grad_set]}
    if not outputs["X@GRAD"]:
        return []
    return [Operator(op.block, "py_func_grad", inputs, outputs,
                     dict(op.attrs))]


@register_op("py_func", grad_maker=_py_func_grad_maker,
             host_effect=True)
def py_func(ctx):
    fid = ctx.attr("forward_callable_id")
    fn = _PY_FUNC_REGISTRY[fid]
    xs = ctx.inputs("X")
    out_names = ctx.op.output("Out")
    block = ctx.op.block
    specs = []
    for n in out_names:
        var = block.var(n)
        dims = list(var.shape or ())
        shape = []
        for pos, d in enumerate(dims):
            if d is not None and d >= 0:
                shape.append(d)
            elif pos == 0:  # batch rides along from the first input
                shape.append(int(xs[0].shape[0]))
            else:
                raise ValueError(
                    f"py_func output {n!r} has unknown non-batch dim "
                    f"at position {pos} (shape {dims}); XLA needs "
                    f"static shapes — declare the out var with "
                    f"concrete trailing dims")
        specs.append(jax.ShapeDtypeStruct(
            tuple(shape), to_jnp_dtype(var.dtype or "float32")))

    def _call(*arrays):
        out = fn(*arrays)
        if not isinstance(out, (list, tuple)):
            out = (out,)
        return tuple(np.asarray(o).astype(s.dtype).reshape(s.shape)
                     for o, s in zip(out, specs))

    vals = io_callback(_call, tuple(specs), *xs, ordered=True)
    return {"Out": list(vals)}


@register_op("py_func_grad", differentiable=False, host_effect=True)
def py_func_grad(ctx):
    bid = ctx.attr("backward_callable_id")
    fn = _PY_FUNC_REGISTRY[bid]
    xs = ctx.inputs("X")
    outs = ctx.inputs("Out")
    douts = ctx.inputs("Out@GRAD")
    # an output unused downstream arrives with no grad (EMPTY_VAR ->
    # None); the user's backward sees zeros there, like the reference
    # tolerates partially-used PyLayer outputs
    douts = [jnp.zeros_like(o) if d is None else d
             for d, o in zip(douts, outs)]
    in_names = ctx.op.input("X")
    out_names = ctx.op.input("Out")
    declared = ctx.op.output("X@GRAD")
    # the maker may have filtered no-grad inputs out of X@GRAD; the
    # callable still returns one grad per input — keep only declared
    keep = [i for i, n in enumerate(in_names)
            if grad_var_name(n) in declared]
    skip = set(ctx.attr("backward_skip_vars", []) or [])
    specs = tuple(jax.ShapeDtypeStruct(xs[i].shape, xs[i].dtype)
                  for i in keep)

    def _call(*arrays):
        nx = len(xs)
        no = len(outs)
        a_x = arrays[:nx]
        a_out = arrays[nx:nx + no]
        a_dout = arrays[nx + no:]
        # reference skip_vars_in_backward_input: the backward callable
        # receives (x..., out..., dout...) minus the skipped vars
        args = [a for a, n in zip(a_x, in_names) if n not in skip]
        args += [a for a, n in zip(a_out, out_names) if n not in skip]
        args += list(a_dout)
        gx = fn(*args)
        if not isinstance(gx, (list, tuple)):
            gx = (gx,)
        if len(gx) == len(xs):  # callable returned grads for ALL inputs
            gx = [gx[i] for i in keep]
        return tuple(np.asarray(g).astype(s.dtype).reshape(s.shape)
                     for g, s in zip(gx, specs))

    vals = io_callback(_call, specs, *xs, *outs, *douts, ordered=True)
    return {"X@GRAD": list(vals)}


# ---------------------------------------------------------------------
def _extract_chunks(seq, scheme, num_types, excluded):
    """Decode (start, end, type) chunks from a tag sequence (reference
    chunk_eval_op.h: IOB=2 tags/type {B,I}, IOE=2 {I,E}, IOBES=4
    {B,I,E,S}, plain=1). Out-of-range tags are 'O'."""
    chunks = []
    tags_per = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
    i = 0
    n = len(seq)
    while i < n:
        tag = int(seq[i])
        if tag < 0 or tag >= num_types * tags_per:
            i += 1
            continue
        ctype = tag // tags_per
        pos = tag % tags_per
        start = i
        counted = True
        if scheme == "plain":
            while i + 1 < n and int(seq[i + 1]) == tag:
                i += 1
        elif scheme == "IOB":  # B=0, I=1; I continues a B chunk
            while i + 1 < n and int(seq[i + 1]) == ctype * 2 + 1:
                i += 1
        elif scheme == "IOE":  # I=0, E=1; chunk = I* then final E
            if pos == 0:
                while i + 1 < n and int(seq[i + 1]) == ctype * 2:
                    i += 1
                if i + 1 < n and int(seq[i + 1]) == ctype * 2 + 1:
                    i += 1  # include the terminating E
            # pos == 1: lone E is a complete chunk
        else:  # IOBES: B=0, I=1, E=2, S=3; only B or S start chunks
            if pos in (1, 2):
                counted = False  # stray I/E without B: not a chunk
            elif pos == 0:
                while (i + 1 < n and int(seq[i + 1]) // 4 == ctype
                       and int(seq[i + 1]) % 4 in (1, 2)):
                    i += 1
                    if int(seq[i]) % 4 == 2:
                        break
            # pos == 3 (S): single-token chunk
        if counted and ctype not in excluded:
            chunks.append((start, i, ctype))
        i += 1
    return set(chunks)


@register_op("chunk_eval", differentiable=False, host_effect=True)
def chunk_eval(ctx):
    """reference chunk_eval_op.cc. Inference/Label: int64 [B, T] padded
    (lengths via the @SEQ_LEN companion when present, else full T)."""
    inference = ctx.input("Inference")
    label = ctx.input("Label")
    seq_len = ctx.input("SeqLength")
    scheme = ctx.attr("chunk_scheme", "IOB")
    num_types = ctx.attr("num_chunk_types")
    excluded = set(ctx.attr("excluded_chunk_types", []) or [])

    b = inference.shape[0]
    # int32 counters: jax canonicalizes int64 away without x64 mode
    specs = (jax.ShapeDtypeStruct((1,), jnp.float32),) * 3 + \
        (jax.ShapeDtypeStruct((1,), jnp.int32),) * 3

    def _eval(inf, lab, lens):
        inf = np.asarray(inf).reshape(b, -1)
        lab = np.asarray(lab).reshape(b, -1)
        n_inf = n_lab = n_cor = 0
        for i in range(b):
            L = int(lens[i]) if lens is not None else inf.shape[1]
            ci = _extract_chunks(inf[i][:L], scheme, num_types,
                                 excluded)
            cl = _extract_chunks(lab[i][:L], scheme, num_types,
                                 excluded)
            n_inf += len(ci)
            n_lab += len(cl)
            n_cor += len(ci & cl)
        p = n_cor / n_inf if n_inf else 0.0
        r = n_cor / n_lab if n_lab else 0.0
        f1 = 2 * p * r / (p + r) if p + r else 0.0
        i32 = np.int32
        return (np.asarray([p], np.float32),
                np.asarray([r], np.float32),
                np.asarray([f1], np.float32),
                np.asarray([n_inf], i32), np.asarray([n_lab], i32),
                np.asarray([n_cor], i32))

    if seq_len is not None:
        vals = io_callback(_eval, specs, inference, label, seq_len,
                           ordered=True)
    else:
        vals = io_callback(lambda a, c: _eval(a, c, None), specs,
                           inference, label, ordered=True)
    p, r, f1, ni, nl, nc = vals
    return {"Precision": p, "Recall": r, "F1-Score": f1,
            "NumInferChunks": ni, "NumLabelChunks": nl,
            "NumCorrectChunks": nc}


# ---------------------------------------------------------------------
_GO_THREADS: List[threading.Thread] = []
_GO_ERRORS: List[BaseException] = []


@register_op("go", differentiable=False, host_effect=True)
def go_op(ctx):
    """reference csp/go_op.cc: execute the sub-block concurrently
    (fire-and-forget goroutine). Inputs are snapshot into the thread;
    the block runs eagerly host-side. Failures are collected and
    re-raised by wait_all_go()."""
    sub_block = ctx.attr("sub_block")
    names = ctx.op.input("X")
    vals = ctx.inputs("X")

    def _launch(*arrays):
        env = {n: np.asarray(a) for n, a in zip(names, arrays)}

        def run():
            from ..core.registry import run_op

            try:
                for op in sub_block.ops:
                    run_op(op, env)
            except BaseException as e:
                _GO_ERRORS.append(e)

        _GO_THREADS[:] = [x for x in _GO_THREADS if x.is_alive()]
        t = threading.Thread(target=run, daemon=True)
        _GO_THREADS.append(t)
        t.start()
        return np.int32(0)

    io_callback(_launch, jax.ShapeDtypeStruct((), jnp.int32), *vals,
                ordered=True)
    return {}


def wait_all_go():
    """Join all goroutines; re-raises the first goroutine failure."""
    while _GO_THREADS:
        _GO_THREADS.pop().join()
    if _GO_ERRORS:
        raise _GO_ERRORS.pop(0)
