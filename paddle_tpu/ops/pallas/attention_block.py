"""Whole-layer fused attention block — the PERF.md MFU lever
("whole-layer pallas fusion", named since round 2, prepped here so the
on-chip A/B is a 10-minute job when the tunnel returns).

One kernel computes the ENTIRE self-attention sub-layer

    out = ((split_heads(x @ Wqkv) -> softmax(scale*QK^T [causal]) @ V)
           merged) @ Wo

so the QKV/context intermediates and the [T,T] score matrices never
touch HBM (the unfused path round-trips all of them between the four
XLA fusions), and the normalized probabilities are saved ONCE in bf16:
the backward kernel does ZERO exps (PERF.md "the exp floor": v5e VPU
exp throughput is the attention bound; re-exping in backward doubles
it) and recomputes only matmul-bound quantities (QKV, context).

Layout contract matches models/transformer.multi_head_attention's
self-attention branch: x [B,T,D], Wqkv [D,3D] (q|k|v concatenated,
then head-split [T,H,Dh]), Wo [D,D], no projection biases, no
residual (the caller's add+LN stays outside — XLA fuses it anyway).

Gating: `usable()`; A/B knobs:
    PADDLE_TPU_FUSE_ATTN_BLOCK=1   route multi_head_attention here
    PADDLE_TPU_DISABLE_PALLAS_ATTN_BLOCK=1  jnp fallback inside the op
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from . import on_tpu
from .attention import _interp

__all__ = ["attention_block", "attention_block_reference", "usable"]

# batch rows per program. VMEM at the routed ceiling (T=512, D=1024):
# fwd per row keeps qkv [T,3D] f32 (6 MB) + one [T,T] f32 score temp
# (1 MB) + weights (Wqkv f32 12 MB shared) -- G=2 stays inside the
# ~16 MB budget the sdpa_short kernel validated on v5e; at the bench
# shape (T=256, D=512) the same G leaves headroom to raise later.
_GROUP_FWD = 2
_GROUP_BWD = 1


def usable(x, w_qkv, n_heads) -> bool:
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS_ATTN_BLOCK") == "1":
        return False
    if not (on_tpu() or _interp()):
        return False
    if x.ndim != 3 or w_qkv.ndim != 2:
        return False
    b, t, d = x.shape
    if w_qkv.shape != (d, 3 * d) or d % n_heads:
        return False
    dh = d // n_heads
    if not (8 <= t <= 512 and t % 8 == 0 and dh % 8 == 0
            and b % _GROUP_FWD == 0 and b % _GROUP_BWD == 0):
        return False
    # explicit VMEM estimate (f32 words) — a too-big shape must fall
    # back to jnp rather than risk a Mosaic VMEM failure on the chip
    # (CLAUDE.md tunnel rules: a hung/killed TPU compile can take the
    # tunnel down for the session). Forward per program: Wqkv + Wo
    # f32 copies + per-row qkv/ctx + one [T,T] score + x/out rows.
    vmem = (d * 3 * d + d * d            # weights (f32 in-kernel)
            + _GROUP_FWD * (2 * t * 3 * d + 2 * t * d + t * t))
    return vmem * 4 <= 12 * 1024 * 1024


def _causal_iota(t):
    r = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    return r >= c


def attention_block_reference(x, w_qkv, w_o, n_heads, scale, causal):
    """jnp oracle/fallback — same math, one op at a time."""
    b, t, d = x.shape
    dh = d // n_heads
    xf = x.astype(jnp.float32)
    qkv = xf @ w_qkv.astype(jnp.float32)            # [B,T,3D]
    q, k, v = jnp.split(qkv, 3, axis=2)

    def heads(z):                                    # [B,T,H,Dh]
        return z.reshape(b, t, n_heads, dh)

    q, k, v = heads(q), heads(k), heads(v)
    s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        s = jnp.where(_causal_iota(t), s, jnp.finfo(s.dtype).min)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhts,bshd->bthd", p, v).reshape(b, t, d)
    return (ctx @ w_o.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def attention_block(x, w_qkv, w_o, n_heads, scale, causal):
    """x [B,T,D], w_qkv [D,3D], w_o [D,D] -> [B,T,D]."""
    out, _ = _fwd_impl(x, w_qkv, w_o, n_heads, scale, causal,
                       save_p=False)
    return out


def _fwd(x, w_qkv, w_o, n_heads, scale, causal):
    out, p = _fwd_impl(x, w_qkv, w_o, n_heads, scale, causal,
                       save_p=True)
    return out, (x, w_qkv, w_o, p)


def _bwd(n_heads, scale, causal, res, g):
    x, w_qkv, w_o, p = res
    return _bwd_impl(x, w_qkv, w_o, p, g, n_heads, scale, causal)


attention_block.defvjp(_fwd, _bwd)


def _fwd_impl(x, w_qkv, w_o, n_heads, scale, causal, save_p):
    from jax.experimental import pallas as pl

    b, t, d = x.shape
    dh = d // n_heads
    grp = _GROUP_FWD

    def kernel(x_ref, wqkv_ref, wo_ref, o_ref, p_ref=None):
        wqkv = wqkv_ref[...].astype(jnp.float32)
        wo = wo_ref[...].astype(jnp.float32)
        for g_i in range(grp):          # static unroll: 2-D MXU dots
            xf = x_ref[g_i].astype(jnp.float32)      # [T,D]
            qkv = xf @ wqkv                          # [T,3D]
            ctx_heads = []
            for h_i in range(n_heads):
                qh = qkv[:, h_i * dh:(h_i + 1) * dh] * scale
                kh = qkv[:, d + h_i * dh:d + (h_i + 1) * dh]
                vh = qkv[:, 2 * d + h_i * dh:2 * d + (h_i + 1) * dh]
                s = qh @ kh.T                        # [T,T]
                if causal:
                    s = jnp.where(_causal_iota(t), s, -jnp.inf)
                m = jnp.max(s, axis=1)
                p = jnp.exp(s - m[:, None])
                l = jnp.sum(p, axis=1)
                pn = p / l[:, None]
                if p_ref is not None:
                    # bf16 saved-P: backward reads it back instead of
                    # re-exping (the whole point of the fusion)
                    p_ref[g_i, h_i] = pn.astype(p_ref.dtype)
                ctx_heads.append(pn @ vh)            # [T,Dh]
            ctx = jnp.concatenate(ctx_heads, axis=1)  # [T,D]
            o_ref[g_i] = (ctx @ wo).astype(o_ref.dtype)

    x_spec = pl.BlockSpec((grp, t, d), lambda i: (i, 0, 0))
    w_qkv_spec = pl.BlockSpec((d, 3 * d), lambda i: (0, 0))
    w_o_spec = pl.BlockSpec((d, d), lambda i: (0, 0))
    out_specs = [x_spec]
    out_shape = [jax.ShapeDtypeStruct((b, t, d), x.dtype)]
    if save_p:
        out_specs.append(
            pl.BlockSpec((grp, n_heads, t, t), lambda i: (i, 0, 0, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b, n_heads, t, t), jnp.bfloat16))
    res = pl.pallas_call(
        kernel,
        grid=(b // grp,),
        in_specs=[x_spec, w_qkv_spec, w_o_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interp(),
    )(x, w_qkv, w_o)
    if save_p:
        return res[0], res[1]
    return res[0], None


def _bwd_impl(x, w_qkv, w_o, p, g, n_heads, scale, causal):
    from jax.experimental import pallas as pl

    b, t, d = x.shape
    dh = d // n_heads
    grp = _GROUP_BWD
    n_prog = b // grp

    def kernel(x_ref, wqkv_ref, wo_ref, p_ref, g_ref,
               dx_ref, dwqkv_ref, dwo_ref):
        wqkv = wqkv_ref[...].astype(jnp.float32)
        wo = wo_ref[...].astype(jnp.float32)
        dwqkv = jnp.zeros((d, 3 * d), jnp.float32)
        dwo = jnp.zeros((d, d), jnp.float32)
        for g_i in range(grp):
            xf = x_ref[g_i].astype(jnp.float32)          # [T,D]
            gg = g_ref[g_i].astype(jnp.float32)          # [T,D]
            qkv = xf @ wqkv                              # recompute
            # context recompute (matmul-bound, zero exps)
            ctx_heads = []
            for h_i in range(n_heads):
                vh = qkv[:, 2 * d + h_i * dh:2 * d + (h_i + 1) * dh]
                pn = p_ref[g_i, h_i].astype(jnp.float32)
                ctx_heads.append(pn @ vh)
            ctx = jnp.concatenate(ctx_heads, axis=1)     # [T,D]
            dwo = dwo + ctx.T @ gg
            dctx = gg @ wo.T                             # [T,D]
            dqkv_cols = []
            dk_cols = []
            dv_cols = []
            for h_i in range(n_heads):
                qh = qkv[:, h_i * dh:(h_i + 1) * dh]
                kh = qkv[:, d + h_i * dh:d + (h_i + 1) * dh]
                vh = qkv[:, 2 * d + h_i * dh:2 * d + (h_i + 1) * dh]
                pn = p_ref[g_i, h_i].astype(jnp.float32)
                dctx_h = dctx[:, h_i * dh:(h_i + 1) * dh]
                dv_cols.append(pn.T @ dctx_h)
                dpn = dctx_h @ vh.T                      # [T,T]
                row = jnp.sum(dpn * pn, axis=1)
                ds = pn * (dpn - row[:, None])           # no exp
                dqkv_cols.append((ds @ kh) * scale)
                dk_cols.append((ds.T @ qh) * scale)
            dqkv = jnp.concatenate(
                dqkv_cols + dk_cols + dv_cols, axis=1)   # [T,3D]
            dwqkv = dwqkv + xf.T @ dqkv
            dx_ref[g_i] = (dqkv @ wqkv.T).astype(dx_ref.dtype)
        dwqkv_ref[0] = dwqkv
        dwo_ref[0] = dwo

    x_spec = pl.BlockSpec((grp, t, d), lambda i: (i, 0, 0))
    p_spec = pl.BlockSpec((grp, n_heads, t, t),
                          lambda i: (i, 0, 0, 0))
    dx, dwqkv_part, dwo_part = pl.pallas_call(
        kernel,
        grid=(n_prog,),
        in_specs=[x_spec,
                  pl.BlockSpec((d, 3 * d), lambda i: (0, 0)),
                  pl.BlockSpec((d, d), lambda i: (0, 0)),
                  p_spec, x_spec],
        out_specs=[x_spec,
                   pl.BlockSpec((1, d, 3 * d), lambda i: (i, 0, 0)),
                   pl.BlockSpec((1, d, d), lambda i: (i, 0, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, d), x.dtype),
            jax.ShapeDtypeStruct((n_prog, d, 3 * d), jnp.float32),
            jax.ShapeDtypeStruct((n_prog, d, d), jnp.float32),
        ],
        interpret=_interp(),
    )(x, w_qkv, w_o, p, g)
    # partial-per-program weight grads summed by XLA (one reduce over
    # a [B/G, D, 3D] buffer -- negligible next to the matmuls)
    return (dx,
            jnp.sum(dwqkv_part, axis=0).astype(w_qkv.dtype),
            jnp.sum(dwo_part, axis=0).astype(w_o.dtype))
