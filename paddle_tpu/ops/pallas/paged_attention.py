"""Paged decode-attention Pallas kernel (single-query, block tables).

The device-side half of the paged KV layout (models/decode_engine.py):
every decode tick, each lane attends its generated prefix whose K/V
live scattered across a SHARED block pool behind the lane's block
table. The serving path today lowers this as gather-to-dense + masked
softmax through ordinary ops (decode_engine._PagedLaneCache) — correct
everywhere, but it materializes a [R, H, maxT, Dh] K/V view per tick.
This kernel streams pool blocks through VMEM page by page with online
softmax instead (the vLLM PagedAttention shape, expressed per the
Pallas conventions of ops/pallas/attention.py), so the dense view
never exists.

STATUS: stub for when the chip returns — validated against the jnp
reference in interpret mode (tests/test_paged_decode.py), NOT routed
into the decode programs yet: the repo convention (CLAUDE.md) requires
an A/B on the real TPU before routing, and the tunnel has been dead
since r2. `usable()` gates exactly like the flash kernels; the jnp
composition in decode_engine stays the fallback either way.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .attention import _interp


def usable(q, pool_k, block_tab) -> bool:
    """Gate: real TPU (or forced interpret mode), pool/table shapes
    consistent, lane-friendly head dims."""
    import os

    from . import on_tpu

    if os.environ.get("PADDLE_TPU_DISABLE_PAGED_ATTN") == "1":
        return False
    if not (on_tpu() or _interp()):
        return False
    r, h, d = q.shape
    nb, bs, hp, dp = pool_k.shape
    return (hp == h and dp == d and d % 8 == 0 and bs % 8 == 0
            and block_tab.shape[0] == r)


def paged_decode_attention_reference(q, pool_k, pool_v, block_tab,
                                     step, scale=1.0):
    """jnp oracle (the math decode_engine's gather path lowers to):
    q [R,H,Dh]; pool_k/pool_v [NB,BS,H,Dh]; block_tab [R,NP] int32;
    step [R] int32 — positions > step are masked. Returns [R,H,Dh]."""
    r, h, d = q.shape
    nb, bs, _, _ = pool_k.shape
    np_pages = block_tab.shape[1]
    t = np_pages * bs
    kv_k = pool_k[block_tab].reshape(r, t, h, d)
    kv_v = pool_v[block_tab].reshape(r, t, h, d)
    s = jnp.einsum("rhd,rthd->rht", q.astype(jnp.float32),
                   kv_k.astype(jnp.float32)) * scale
    pos = jnp.arange(t, dtype=jnp.int32)
    s = jnp.where(pos[None, None, :] <= step[:, None, None], s, -1e9)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("rht,rthd->rhd", p,
                      kv_v.astype(jnp.float32)).astype(q.dtype)


def paged_decode_attention(q, pool_k, pool_v, block_tab, step,
                           scale=1.0):
    """Pallas lowering: grid over lanes; per lane, stream NP pool
    blocks (dynamically addressed through the lane's table row)
    through VMEM with the online-softmax carry — no [R,H,maxT,Dh]
    gather ever materializes."""
    from jax.experimental import pallas as pl

    r, h, d = q.shape
    nb, bs, _, _ = pool_k.shape
    np_pages = block_tab.shape[1]
    kernel = functools.partial(_paged_kernel, scale=scale, bs=bs,
                               np_pages=np_pages)
    out = pl.pallas_call(
        kernel,
        grid=(r,),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),
            # the WHOLE pool is visible to every program: blocks are
            # dynamically addressed via the table, which BlockSpec
            # index maps cannot express (they see only grid indices)
            pl.BlockSpec((nb, bs, h, d), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((nb, bs, h, d), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((1, np_pages), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((r, h, d), q.dtype),
        interpret=_interp(),
    )(q, pool_k, pool_v,
      block_tab.astype(jnp.int32),
      step.reshape(r, 1).astype(jnp.int32))
    return out


def _paged_kernel(q_ref, kpool_ref, vpool_ref, tab_ref, step_ref,
                  o_ref, *, scale, bs, np_pages):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale          # [H, Dh]
    h, d = q.shape
    st = step_ref[0, 0]
    m = jnp.full((h,), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((h,), dtype=jnp.float32)
    acc = jnp.zeros((h, d), dtype=jnp.float32)

    def body(p, carry):
        m, l, acc = carry
        b = tab_ref[0, p]
        k_blk = pl.load(kpool_ref, (pl.dslice(b, 1), slice(None),
                                    slice(None), slice(None)))[0]
        v_blk = pl.load(vpool_ref, (pl.dslice(b, 1), slice(None),
                                    slice(None), slice(None)))[0]
        # s[h, pos]: one dot per head over the block's BS positions
        s = jnp.einsum("hd,shd->hs", q,
                       k_blk.astype(jnp.float32))
        pos = p * bs + jax.lax.broadcasted_iota(jnp.int32, (h, bs), 1)
        s = jnp.where(pos <= st, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=1))
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        pr = jnp.where(jnp.isfinite(s),
                       jnp.exp(s - m_safe[:, None]), 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + pr.sum(axis=1)
        acc_new = acc * corr[:, None] + jnp.einsum(
            "hs,shd->hd", pr, v_blk.astype(jnp.float32))
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, np_pages, body, (m, l, acc))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / safe_l[:, None]).astype(o_ref.dtype)
