"""Pallas TPU kernels for the genuinely hot paths (SURVEY.md §7 step 7):
flash attention, layer_norm. Each module exposes usable() gating so ops
fall back to jnp compositions off-TPU or on unsupported shapes.

Shared helpers live here so backend detection and the attention oracle
exist exactly once (kernel modules and the nn_ops fallback all import
them).
"""
import jax
import jax.numpy as jnp


def on_tpu() -> bool:
    """True when the default backend is a real or tunneled TPU."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def reference_attention(q, k, v, scale, causal):
    """Masked-softmax attention oracle: q,k,v [B,H,T,D] -> [B,H,T,D].

    Used as the custom_vjp backward composition for the flash kernel and
    as the off-TPU forward fallback. Masking uses finfo.min (not -inf) so
    fully-masked rows yield a uniform distribution instead of NaN.
    """
    logits = jnp.einsum("bhqd,bhkd->bhqk",
                        q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), dtype=bool), tk - tq)
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v.astype(
        jnp.float32)).astype(q.dtype)
