"""Pallas TPU kernels for the genuinely hot paths (SURVEY.md §7 step 7):
flash attention, layer_norm. Each module exposes usable() gating so ops
fall back to jnp compositions off-TPU or on unsupported shapes."""
