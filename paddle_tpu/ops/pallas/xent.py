"""Fused softmax-cross-entropy Pallas kernels.

Parity: reference softmax_with_cross_entropy_op.cu (the fused CUDA
kernel pair). TPU motivation (profiled on v5e, transformer-base
128x256x32000): the jnp composition upcasts logits to fp32 for the
stable logsumexp, and XLA materializes that f32 [N,V] buffer (4 GB)
in HBM because forward loss, picked-logit gather and backward all
consume it. These kernels stream bf16 logits through VMEM row-blocks
and keep every fp32 intermediate on-chip:

  forward:  loss = (1-eps)*(lse - picked) + eps*(lse - mean)   [+ lse out]
  backward: dlogits = (softmax - (1-eps)*onehot - eps/V) * g
            with lse recomputed in-kernel -- ONE bf16 read of the
            logits, one bf16 write of the grad, no residuals.

Hard labels only (soft-label programs take the jnp path).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import on_tpu
from .attention import _interp

_ROW_BLOCK = 32  # bn x V fp32 temps stay ~4 MB in VMEM at V=32k


def usable(logits2d, label1d) -> bool:
    import os

    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS_XENT") == "1":
        return False
    if not (on_tpu() or _interp()):
        return False
    n, v = logits2d.shape
    return (n % _ROW_BLOCK == 0 and v % 128 == 0
            and label1d.shape == (n,))


# ---------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------
def _fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref, *, eps, v, ignore):
    x = x_ref[...].astype(jnp.float32)          # [bn, V]
    bn = x.shape[0]
    m = jnp.max(x, axis=1)
    ex = jnp.exp(x - m[:, None])
    lse = m + jnp.log(jnp.sum(ex, axis=1))
    lab = lab_ref[..., 0]                       # [bn] int32
    cols = jax.lax.broadcasted_iota(jnp.int32, (bn, v), 1)
    picked = jnp.sum(jnp.where(cols == lab[:, None], x, 0.0), axis=1)
    loss = lse - picked
    if eps:
        uniform = lse - jnp.mean(x, axis=1)
        loss = (1.0 - eps) * loss + eps * uniform
    # ignore_index rows contribute 0 loss (reference
    # softmax_with_cross_entropy_op.h hard-label semantics)
    loss_ref[..., 0] = jnp.where(lab == ignore, 0.0, loss)
    lse_ref[..., 0] = lse


def xent_forward(logits2d, label1d, eps=0.0, ignore_index=-100):
    """bf16/f32 [N,V] + int32 [N] -> (loss f32 [N], lse f32 [N])."""
    from jax.experimental import pallas as pl

    n, v = logits2d.shape
    bn = _ROW_BLOCK
    kernel = functools.partial(_fwd_kernel, eps=float(eps), v=v,
                               ignore=int(ignore_index))
    # per-row vectors ride as [N,1]: rank-1 blocks of bn<128 rows are
    # rejected by the TPU lowering (lane dim must be full or 128-mult)
    loss, lse = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, v), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=_interp(),
    )(logits2d, label1d.astype(jnp.int32)[:, None])
    return loss[:, 0], lse[:, 0]


# ---------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------
def _bwd_kernel(x_ref, lab_ref, g_ref, dx_ref, *, eps, v, ignore):
    x = x_ref[...].astype(jnp.float32)
    bn = x.shape[0]
    m = jnp.max(x, axis=1)
    ex = jnp.exp(x - m[:, None])
    denom = jnp.sum(ex, axis=1)
    sm = ex / denom[:, None]
    lab = lab_ref[..., 0]
    cols = jax.lax.broadcasted_iota(jnp.int32, (bn, v), 1)
    onehot = (cols == lab[:, None]).astype(jnp.float32)
    tgt = (1.0 - eps) * onehot + (eps / v if eps else 0.0)
    g = g_ref[..., 0].astype(jnp.float32)
    g = jnp.where(lab == ignore, 0.0, g)  # ignored rows: zero grad
    dx_ref[...] = ((sm - tgt) * g[:, None]).astype(dx_ref.dtype)


def xent_backward(logits2d, label1d, dloss1d, eps=0.0,
                  ignore_index=-100):
    """dlogits in the logits' storage dtype; lse recomputed on-chip."""
    from jax.experimental import pallas as pl

    n, v = logits2d.shape
    bn = _ROW_BLOCK
    kernel = functools.partial(_bwd_kernel, eps=float(eps), v=v,
                               ignore=int(ignore_index))
    return pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, v), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, v), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, v), logits2d.dtype),
        interpret=_interp(),
    )(logits2d, label1d.astype(jnp.int32)[:, None],
      dloss1d.astype(jnp.float32)[:, None])


def maybe_route(logits, label):
    """Shared gate + label normalization for the swce forward AND grad
    kernels (they must route identically): returns
    (logits2d, label1d) when the pallas kernels apply, else None."""
    lab = label.astype(jnp.int32)
    if lab.ndim == logits.ndim:
        lab = lab[..., 0]
    l2 = logits.reshape(-1, logits.shape[-1])
    lab1 = lab.reshape(-1)
    if usable(l2, lab1):
        return l2, lab1
    return None
