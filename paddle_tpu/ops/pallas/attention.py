"""Flash attention Pallas TPU kernels (forward AND backward).

The hot path of the Transformer benchmark (BASELINE.md config 3). Online-
softmax tiling keeps the full [Tq,Tk] logits matrix out of HBM: per
(batch*head, q-block) grid cell we stream k/v blocks through VMEM,
carrying running max/denominator -- the standard flash pattern expressed
in Pallas (see /opt/skills/guides/pallas_guide.md).

Backward: the forward additionally writes the per-row logsumexp; the
backward recomputes attention probabilities blockwise from (q, k, lse)
and accumulates dq in one kernel (grid over q-blocks) and dk/dv in a
second (grid over k-blocks) -- the FlashAttention-2 recipe. Residuals
are q, k, v, out, lse: O(T) extra memory instead of the O(T^2)
probability matrix, and no jnp fallback on the grad path.

Both directions use BOTTOM-RIGHT causal alignment (query i sees keys
<= i + tk - tq), the same convention as the jnp fallback in
ops/nn_ops.py, so kernel/fallback numerics agree for tq != tk.

Block sizes adapt to the sequence length (min(t, 256) when divisible),
so the kernels engage for seq-128 benchmark shapes, not just multiples
of 256. `force_interpret(True)` runs every pallas_call in interpreter
mode so CPU tests can exercise the real kernel code paths.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_MAX_BLOCK = 256

_INTERPRET = [False]


def force_interpret(on: bool = True) -> None:
    """Run kernels in pallas interpreter mode (CPU testing)."""
    _INTERPRET[0] = bool(on)


def _interp() -> bool:
    return _INTERPRET[0]


def _pick_block(t: int) -> int:
    for b in (_MAX_BLOCK, 128, 64, 32, 16, 8):
        if t % b == 0:
            return b
    return 0


def usable(q, k, v) -> bool:
    import os

    from . import on_tpu

    if os.environ.get("PADDLE_TPU_DISABLE_FLASH_ATTN") == "1":
        return False  # perf-debug escape hatch: XLA attention path
    if not (on_tpu() or _interp()):
        return False
    b, h, tq, d = q.shape
    tk = k.shape[2]
    return (_pick_block(tq) >= 8 and _pick_block(tk) >= 8
            and d in (64, 128, 256) and q.dtype == k.dtype == v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, scale=1.0, causal=False):
    """q,k,v: [B,H,T,D] -> [B,H,T,D]."""
    out, _ = _flash_fwd_impl(q, k, v, scale, causal)
    return out


def _flash_fwd(q, k, v, scale, causal):
    out, lse = _flash_fwd_impl(q, k, v, scale, causal)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_impl(q, k, v, out, lse, g, scale, causal)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def _flash_fwd_impl(q, k, v, scale, causal):
    from jax.experimental import pallas as pl

    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = _pick_block(tq)
    block_k = _pick_block(tk)
    bh = b * h
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)

    grid = (bh, tq // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               tq=tq, tk=tk, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            # lse rides as (bh, 1, tq): sublane dim 1 == array dim, lane
            # dim block_q is 128-divisible (TPU BlockSpec constraint)
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32),
        ],
        interpret=_interp(),
    )(q3, k3, v3)
    return out.reshape(b, h, tq, d), lse.reshape(b, h, tq)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                tq, tk, block_k):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale  # [BQ, D]
    block_q = q.shape[0]
    qi = pl.program_id(1)
    m = jnp.full((block_q,), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((block_q,), dtype=jnp.float32)
    acc = jnp.zeros(q.shape, dtype=jnp.float32)
    # bottom-right causal alignment: query row i attends keys
    # <= i + (tk - tq), matching the jnp fallback's tril offset.
    offset = tk - tq

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k)].astype(
            jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k)].astype(
            jnp.float32)
        s = q @ k_blk.T  # [BQ, BK]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=1))
        # rows with no valid key yet keep m=-inf; guard the exp
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        correction = jnp.where(jnp.isfinite(m),
                               jnp.exp(m - m_safe), 0.0)
        l_new = l * correction + p.sum(axis=1)
        acc_new = acc * correction[:, None] + p @ v_blk
        return m_new, l_new, acc_new

    n_blocks = tk // block_k
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m, l, acc))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / safe_l[:, None]).astype(o_ref.dtype)
    # lse = m + log(l); -inf for fully-masked rows (p will be 0 in bwd)
    lse_ref[0, 0] = jnp.where(l > 0.0, m + jnp.log(safe_l), -jnp.inf)


# ---------------------------------------------------------------------------
# backward (FlashAttention-2): dq over q-blocks, dk/dv over k-blocks
# ---------------------------------------------------------------------------
def _flash_bwd_impl(q, k, v, out, lse, g, scale, causal):
    from jax.experimental import pallas as pl

    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = _pick_block(tq)
    block_k = _pick_block(tk)
    bh = b * h
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)
    g3 = g.reshape(bh, tq, d)
    lse3 = lse.reshape(bh, 1, tq)
    # delta_i = rowsum(dO_i * O_i); tiny elementwise+reduce, XLA fuses
    delta = jnp.sum(g3.astype(jnp.float32)
                    * out.reshape(bh, tq, d).astype(jnp.float32),
                    axis=-1).reshape(bh, 1, tq)

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale,
                                  causal=causal, tq=tq, tk=tk,
                                  block_k=block_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        interpret=_interp(),
    )(q3, k3, v3, g3, lse3, delta)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale,
                                   causal=causal, tq=tq, tk=tk,
                                   block_q=block_q)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, tq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        interpret=_interp(),
    )(q3, k3, v3, g3, lse3, delta)

    return (dq.reshape(b, h, tq, d), dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d))


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, scale, causal, tq, tk, block_k):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32)          # [BQ, D]
    do = do_ref[0].astype(jnp.float32)        # [BQ, D]
    lse = lse_ref[0, 0]                       # [BQ]
    delta = delta_ref[0, 0]                   # [BQ]
    block_q = q.shape[0]
    qi = pl.program_id(1)
    offset = tk - tq
    lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)[:, None]
    dq = jnp.zeros(q.shape, dtype=jnp.float32)

    def body(kb, dq):
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k)].astype(
            jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k)].astype(
            jnp.float32)
        s = (q @ k_blk.T) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - lse_safe), 0.0)
        dp = do @ v_blk.T                     # [BQ, BK]
        ds = p * (dp - delta[:, None]) * scale
        return dq + ds @ k_blk

    n_blocks = tk // block_k
    dq = jax.lax.fori_loop(0, n_blocks, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, tq, tk, block_q):
    from jax.experimental import pallas as pl

    k = k_ref[0].astype(jnp.float32)          # [BK, D]
    v = v_ref[0].astype(jnp.float32)          # [BK, D]
    block_k = k.shape[0]
    ki = pl.program_id(1)
    offset = tk - tq
    dk = jnp.zeros(k.shape, dtype=jnp.float32)
    dv = jnp.zeros(v.shape, dtype=jnp.float32)

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.dslice(qb * block_q, block_q)].astype(
            jnp.float32)
        do_blk = do_ref[0, pl.dslice(qb * block_q, block_q)].astype(
            jnp.float32)
        lse_blk = lse_ref[0, 0, pl.dslice(qb * block_q, block_q)]
        delta_blk = delta_ref[0, 0, pl.dslice(qb * block_q, block_q)]
        lse_safe = jnp.where(jnp.isfinite(lse_blk), lse_blk, 0.0)[:, None]
        s = (q_blk @ k.T) * scale             # [BQ, BK]
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - lse_safe), 0.0)
        dv = dv + p.T @ do_blk
        dp = do_blk @ v.T                     # [BQ, BK]
        ds = p * (dp - delta_blk[:, None]) * scale
        dk = dk + ds.T @ q_blk
        return dk, dv

    n_blocks = tq // block_q
    dk, dv = jax.lax.fori_loop(0, n_blocks, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# short-sequence fused SDPA: full [T,T] scores live in VMEM, several
# (b,h) rows batched per program.
#
# Why not the flash kernel: at T<=~512 the flash grid degenerates to
# b*h tiny programs (1024 on transformer-base) whose per-program
# launch/DMA overhead dominates (~5 ms/call measured on v5e, slower
# than the jnp composition). Here one program handles _SDPA_GROUP
# heads with the entire score matrix on-chip -- no online-softmax
# rescaling, no HBM [B,H,T,T] buffer (the jnp path's cost), and the
# whole backward (dq, dk, dv) in ONE pass with softmax recomputed
# from the saved lse.
# ---------------------------------------------------------------------------
# VMEM sizing at the routed window's top (T=512, the worst case
# sdpa_usable admits): G*T*T f32 score temps = 8*512*512*4 = 8 MB fwd
# (verified compiling + faster than the jnp path on v5e); the backward
# additionally holds the saved-P block, hence the smaller group.
_SDPA_GROUP_FWD = 8
_SDPA_GROUP_BWD = 4


def sdpa_usable(q, k, v) -> bool:
    import os

    from . import on_tpu

    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS_SDPA") == "1":
        return False
    if not (on_tpu() or _interp()):
        return False
    b, h, tq, d = q.shape
    tk = k.shape[2]
    # measured window on v5e (see module comment): at T<=256 the jnp
    # composition wins in-model (XLA fuses softmax into neighbors and
    # overlaps better); at T>512 the [grp,T,T] f32 scores overflow
    # VMEM (1024^2*4*grp) -- that range belongs to the flash kernel
    if tq != tk or not (256 < tq <= 512) or tq % 8 != 0:
        return False
    if d not in (64, 128) or q.dtype != k.dtype or k.dtype != v.dtype:
        return False
    bh = b * h
    return bh % _SDPA_GROUP_FWD == 0 and bh % _SDPA_GROUP_BWD == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def sdpa_short(q, k, v, scale=1.0, causal=False):
    """q,k,v: [B,H,T,D] (same T) -> [B,H,T,D]."""
    # primal (inference) path: p is only a backward residual; skip
    # materializing the [B*H,T,T] tensor entirely
    out, _ = _sdpa_short_fwd_impl(q, k, v, scale, causal,
                                  save_p=False)
    return out


def _sdpa_short_fwd(q, k, v, scale, causal):
    out, p = _sdpa_short_fwd_impl(q, k, v, scale, causal, save_p=True)
    return out, (q, k, v, p)


def _sdpa_short_bwd(scale, causal, res, g):
    q, k, v, p = res
    return _sdpa_short_bwd_impl(q, k, v, p, g, scale, causal)


sdpa_short.defvjp(_sdpa_short_fwd, _sdpa_short_bwd)


def _causal_mask(t):
    r = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    return r >= c


def _sdpa_short_fwd_impl(q, k, v, scale, causal, save_p):
    from jax.experimental import pallas as pl

    b, h, t, d = q.shape
    bh = b * h
    grp = _SDPA_GROUP_FWD
    q3 = q.reshape(bh, t, d)
    k3 = k.reshape(bh, t, d)
    v3 = v.reshape(bh, t, d)

    def kernel(q_ref, k_ref, v_ref, o_ref, p_ref=None):
        for g_i in range(grp):  # static unroll: 2-D matmuls on the MXU
            qg = q_ref[g_i].astype(jnp.float32) * scale  # [T,D]
            kg = k_ref[g_i].astype(jnp.float32)
            vg = v_ref[g_i].astype(jnp.float32)
            s = qg @ kg.T                                # [T,T]
            if causal:
                s = jnp.where(_causal_mask(t), s, -jnp.inf)
            m = jnp.max(s, axis=1)
            p = jnp.exp(s - m[:, None])
            l = jnp.sum(p, axis=1)
            pn = p / l[:, None]
            o_ref[g_i] = (pn @ vg).astype(o_ref.dtype)
            if p_ref is not None:
                # normalized probabilities saved bf16 for the
                # backward: the VPU's exp throughput (~25G/s on v5e)
                # is the floor of this whole kernel, so the backward
                # must NOT re-exp -- rereading 2*T*T bf16 from HBM is
                # ~7x cheaper than the recompute
                p_ref[g_i] = pn.astype(p_ref.dtype)

    blk_td = pl.BlockSpec((grp, t, d), lambda i: (i, 0, 0))
    out_specs = [blk_td]
    out_shape = [jax.ShapeDtypeStruct((bh, t, d), q.dtype)]
    if save_p:
        out_specs.append(pl.BlockSpec((grp, t, t), lambda i: (i, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((bh, t, t), jnp.bfloat16))
    res = pl.pallas_call(
        kernel,
        grid=(bh // grp,),
        in_specs=[blk_td] * 3,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interp(),
    )(q3, k3, v3)
    if save_p:
        out, p = res
    else:
        out, p = res[0], None
    return out.reshape(b, h, t, d), p


def _sdpa_short_bwd_impl(q, k, v, p, g, scale, causal):
    from jax.experimental import pallas as pl

    b, h, t, d = q.shape
    bh = b * h
    grp = _SDPA_GROUP_BWD
    q3 = q.reshape(bh, t, d)
    k3 = k.reshape(bh, t, d)
    v3 = v.reshape(bh, t, d)
    g3 = g.reshape(bh, t, d)

    def kernel(q_ref, k_ref, v_ref, g_ref, p_ref,
               dq_ref, dk_ref, dv_ref):
        for g_i in range(grp):
            qg = q_ref[g_i].astype(jnp.float32)
            kg = k_ref[g_i].astype(jnp.float32)
            vg = v_ref[g_i].astype(jnp.float32)
            gg = g_ref[g_i].astype(jnp.float32)
            pg = p_ref[g_i].astype(jnp.float32)          # [T,T] saved
            dv_ref[g_i] = (pg.T @ gg).astype(dv_ref.dtype)
            dp = gg @ vg.T                               # [T,T]
            # softmax vjp: ds = p * (dp - rowsum(dp * p)); no exp here
            row = jnp.sum(dp * pg, axis=1)
            ds = pg * (dp - row[:, None])
            dq_ref[g_i] = ((ds @ kg) * scale).astype(dq_ref.dtype)
            dk_ref[g_i] = ((ds.T @ qg) * scale).astype(dk_ref.dtype)

    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(bh // grp,),
        in_specs=[pl.BlockSpec((grp, t, d), lambda i: (i, 0, 0))] * 4
        + [pl.BlockSpec((grp, t, t), lambda i: (i, 0, 0))],
        out_specs=[pl.BlockSpec((grp, t, d), lambda i: (i, 0, 0))] * 3,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        interpret=_interp(),
    )(q3, k3, v3, g3, p)
    return (dq.reshape(b, h, t, d), dk.reshape(b, h, t, d),
            dv.reshape(b, h, t, d))
