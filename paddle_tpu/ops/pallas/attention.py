"""Flash attention Pallas TPU kernel.

The hot path of the Transformer benchmark (BASELINE.md config 3). Online-
softmax tiling keeps the full [Tq,Tk] logits matrix out of HBM: per
(batch*head, q-block) grid cell we stream k/v blocks through VMEM,
carrying running max/denominator -- the standard flash pattern expressed
in Pallas (see /opt/skills/guides/pallas_guide.md).

Differentiation: pallas_call has no autodiff rule, so flash_attention is
a jax.custom_vjp whose backward is the jnp composition (fully fused by
XLA); a Pallas backward kernel is a later optimization. Both paths use
BOTTOM-RIGHT causal alignment (query i sees keys <= i + tk - tq), the
same convention as the jnp fallback in ops/nn_ops.py, so kernel/fallback
numerics agree for tq != tk.

Block sizes adapt to the sequence length (min(t, 256) when divisible),
so the kernel engages for seq-128 benchmark shapes, not just multiples
of 256.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_MAX_BLOCK = 256


def _pick_block(t: int) -> int:
    for b in (_MAX_BLOCK, 128, 64, 32, 16, 8):
        if t % b == 0:
            return b
    return 0


def usable(q, k, v) -> bool:
    from . import on_tpu

    if not on_tpu():
        return False
    b, h, tq, d = q.shape
    tk = k.shape[2]
    return (_pick_block(tq) >= 8 and _pick_block(tk) >= 8
            and d in (64, 128, 256) and q.dtype == k.dtype == v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, scale=1.0, causal=False):
    """q,k,v: [B,H,T,D] -> [B,H,T,D]."""
    return _flash_fwd_impl(q, k, v, scale, causal)


def _reference_attention(q, k, v, scale, causal):
    from . import reference_attention

    return reference_attention(q, k, v, scale, causal)


def _flash_fwd(q, k, v, scale, causal):
    out = _flash_fwd_impl(q, k, v, scale, causal)
    return out, (q, k, v)


def _flash_bwd(scale, causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference_attention(q_, k_, v_, scale,
                                                causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def _flash_fwd_impl(q, k, v, scale, causal):
    from jax.experimental import pallas as pl

    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = _pick_block(tq)
    block_k = _pick_block(tk)
    bh = b * h
    q3 = q.reshape(bh, tq, d)
    k3 = k.reshape(bh, tk, d)
    v3 = v.reshape(bh, tk, d)

    grid = (bh, tq // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               tq=tq, tk=tk, block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
    )(q3, k3, v3)
    return out.reshape(b, h, tq, d)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, tq, tk,
                block_k):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale  # [BQ, D]
    block_q = q.shape[0]
    qi = pl.program_id(1)
    m = jnp.full((block_q,), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((block_q,), dtype=jnp.float32)
    acc = jnp.zeros(q.shape, dtype=jnp.float32)
    # bottom-right causal alignment: query row i attends keys
    # <= i + (tk - tq), matching the jnp fallback's tril offset.
    offset = tk - tq

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k)].astype(
            jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k)].astype(
            jnp.float32)
        s = q @ k_blk.T  # [BQ, BK]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos + offset >= k_pos, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=1))
        # rows with no valid key yet keep m=-inf; guard the exp
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        correction = jnp.where(jnp.isfinite(m),
                               jnp.exp(m - m_safe), 0.0)
        l_new = l * correction + p.sum(axis=1)
        acc_new = acc * correction[:, None] + p @ v_blk
        return m_new, l_new, acc_new

    n_blocks = tk // block_k
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m, l, acc))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / safe_l[:, None]).astype(o_ref.dtype)
