"""Whole-layer fused FFN block — the second half of PERF.md's
"whole-layer pallas fusion (attention+MLP epilogues)" lever.

One kernel computes the position-wise MLP

    out = relu(x @ W1 + b1) @ W2 + b2

so the [T, d_inner] hidden activation (the largest tensor in the
sub-layer: d_inner = 4*d_model) never touches HBM in forward, and the
backward kernel recomputes it from x (matmul-bound — cheaper than the
HBM round-trip at bench shapes) while accumulating dW1/dW2/db per
program.

Layout contract matches models/transformer._ffn with dropout=0:
x [B,T,D], W1 [D,F], b1 [F], W2 [F,D], b2 [D]; residual and the
following layer_norm stay outside (XLA fuses them into neighbors).

Gating mirrors attention_block: routed from the model by
PADDLE_TPU_FUSE_ATTN_BLOCK=1 (one knob = the whole fused layer),
disabled kernel-side by PADDLE_TPU_DISABLE_PALLAS_FFN_BLOCK=1.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from . import on_tpu
from .attention import _interp

__all__ = ["ffn_block", "ffn_block_reference", "usable"]

_GROUP_FWD = 2
_GROUP_BWD = 1


def usable(x, w1) -> bool:
    if os.environ.get("PADDLE_TPU_DISABLE_PALLAS_FFN_BLOCK") == "1":
        return False
    if not (on_tpu() or _interp()):
        return False
    if x.ndim != 3 or w1.ndim != 2:
        return False
    b, t, d = x.shape
    f = w1.shape[1]
    if w1.shape[0] != d:
        return False
    if not (t % 8 == 0 and d % 8 == 0 and f % 8 == 0
            and b % _GROUP_FWD == 0 and b % _GROUP_BWD == 0):
        return False
    # explicit VMEM estimate (f32 words) for BOTH kernels — the
    # backward additionally holds dw1/dw2 accumulators, doubling the
    # weight footprint, and is the binding case for weights-dominated
    # shapes
    fwd = (d * f * 2                        # W1 + W2 (f32 in-kernel)
           + _GROUP_FWD * (2 * t * d + t * f))
    bwd = (d * f * 4                        # W1+W2 + dw1+dw2 accums
           + _GROUP_BWD * (3 * t * d + 3 * t * f))
    return max(fwd, bwd) * 4 <= 12 * 1024 * 1024


def ffn_block_reference(x, w1, b1, w2, b2):
    """jnp oracle/fallback — same math, one op at a time."""
    xf = x.astype(jnp.float32)
    h = jax.nn.relu(xf @ w1.astype(jnp.float32)
                    + b1.astype(jnp.float32))
    out = h @ w2.astype(jnp.float32) + b2.astype(jnp.float32)
    return out.astype(x.dtype)


@jax.custom_vjp
def ffn_block(x, w1, b1, w2, b2):
    """x [B,T,D], w1 [D,F], b1 [F], w2 [F,D], b2 [D] -> [B,T,D]."""
    return _fwd_impl(x, w1, b1, w2, b2)


def _fwd(x, w1, b1, w2, b2):
    return _fwd_impl(x, w1, b1, w2, b2), (x, w1, b1, w2, b2)


def _bwd(res, g):
    return _bwd_impl(*res, g)


ffn_block.defvjp(_fwd, _bwd)


def _fwd_impl(x, w1, b1, w2, b2):
    from jax.experimental import pallas as pl

    b, t, d = x.shape
    f = w1.shape[1]
    grp = _GROUP_FWD

    def kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref):
        w1f = w1_ref[...].astype(jnp.float32)
        w2f = w2_ref[...].astype(jnp.float32)
        b1f = b1_ref[...].astype(jnp.float32)
        b2f = b2_ref[...].astype(jnp.float32)
        for g_i in range(grp):
            xf = x_ref[g_i].astype(jnp.float32)       # [T,D]
            h = jnp.maximum(xf @ w1f + b1f[None], 0.0)  # [T,F] in VMEM
            o_ref[g_i] = (h @ w2f + b2f[None]).astype(o_ref.dtype)

    x_spec = pl.BlockSpec((grp, t, d), lambda i: (i, 0, 0))
    out, = pl.pallas_call(
        kernel,
        grid=(b // grp,),
        in_specs=[x_spec,
                  pl.BlockSpec((d, f), lambda i: (0, 0)),
                  pl.BlockSpec((f,), lambda i: (0,)),
                  pl.BlockSpec((f, d), lambda i: (0, 0)),
                  pl.BlockSpec((d,), lambda i: (0,))],
        out_specs=[x_spec],
        out_shape=[jax.ShapeDtypeStruct((b, t, d), x.dtype)],
        interpret=_interp(),
    )(x, w1, b1, w2, b2)
    return out


def _bwd_impl(x, w1, b1, w2, b2, g):
    from jax.experimental import pallas as pl

    b, t, d = x.shape
    f = w1.shape[1]
    grp = _GROUP_BWD
    n_prog = b // grp

    def kernel(x_ref, w1_ref, w2_ref, b1_ref, g_ref,
               dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref):
        w1f = w1_ref[...].astype(jnp.float32)
        w2f = w2_ref[...].astype(jnp.float32)
        b1f = b1_ref[...].astype(jnp.float32)
        dw1 = jnp.zeros((d, f), jnp.float32)
        db1 = jnp.zeros((f,), jnp.float32)
        dw2 = jnp.zeros((f, d), jnp.float32)
        db2 = jnp.zeros((d,), jnp.float32)
        for g_i in range(grp):
            xf = x_ref[g_i].astype(jnp.float32)
            gg = g_ref[g_i].astype(jnp.float32)
            pre = xf @ w1f + b1f[None]                # recompute [T,F]
            h = jnp.maximum(pre, 0.0)
            dw2 = dw2 + h.T @ gg
            db2 = db2 + jnp.sum(gg, axis=0)
            dh = jnp.where(pre > 0.0, gg @ w2f.T, 0.0)  # relu vjp
            dw1 = dw1 + xf.T @ dh
            db1 = db1 + jnp.sum(dh, axis=0)
            dx_ref[g_i] = (dh @ w1f.T).astype(dx_ref.dtype)
        dw1_ref[0] = dw1
        db1_ref[0] = db1
        dw2_ref[0] = dw2
        db2_ref[0] = db2

    x_spec = pl.BlockSpec((grp, t, d), lambda i: (i, 0, 0))
    dx, dw1p, db1p, dw2p, db2p = pl.pallas_call(
        kernel,
        grid=(n_prog,),
        in_specs=[x_spec,
                  pl.BlockSpec((d, f), lambda i: (0, 0)),
                  pl.BlockSpec((f, d), lambda i: (0, 0)),
                  pl.BlockSpec((f,), lambda i: (0,)),
                  x_spec],
        out_specs=[x_spec,
                   pl.BlockSpec((1, d, f), lambda i: (i, 0, 0)),
                   pl.BlockSpec((1, f), lambda i: (i, 0)),
                   pl.BlockSpec((1, f, d), lambda i: (i, 0, 0)),
                   pl.BlockSpec((1, d), lambda i: (i, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, d), x.dtype),
            jax.ShapeDtypeStruct((n_prog, d, f), jnp.float32),
            jax.ShapeDtypeStruct((n_prog, f), jnp.float32),
            jax.ShapeDtypeStruct((n_prog, f, d), jnp.float32),
            jax.ShapeDtypeStruct((n_prog, d), jnp.float32),
        ],
        interpret=_interp(),
    )(x, w1, w2, b1, g)
    return (dx,
            jnp.sum(dw1p, axis=0).astype(w1.dtype),
            jnp.sum(db1p, axis=0).astype(b1.dtype),
            jnp.sum(dw2p, axis=0).astype(w2.dtype),
            jnp.sum(db2p, axis=0).astype(b2.dtype))
