"""Fused layer_norm Pallas TPU kernel (reference layer_norm_op.cu's
fused-kernel role). One VMEM pass per row-block: mean/var/normalize/
affine without materializing intermediates in HBM. Forward-only -- the
layer_norm op wraps it in custom_vjp with the jnp backward.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def usable(n: int, d: int) -> bool:
    from . import on_tpu

    return on_tpu() and d % 128 == 0 and n >= 8


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm(x, scale, bias, eps=1e-5):
    """x: [N,D]; scale/bias: [D]."""
    return _ln_impl(x, scale, bias, eps)


def _ln_ref(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)[None]
            + bias.astype(jnp.float32)[None]).astype(x.dtype)


def _ln_fwd(x, scale, bias, eps):
    return _ln_impl(x, scale, bias, eps), (x, scale, bias)


def _ln_bwd(eps, res, g):
    x, scale, bias = res
    _, vjp = jax.vjp(lambda x_, s_, b_: _ln_ref(x_, s_, b_, eps),
                     x, scale, bias)
    return vjp(g)


layer_norm.defvjp(_ln_fwd, _ln_bwd)


def _ln_impl(x, scale, bias, eps):
    from jax.experimental import pallas as pl

    n, d = x.shape
    block_n = next((b for b in (256, 128, 64, 32, 8, 1) if n % b == 0))

    def kernel(x_ref, s_ref, b_ref, o_ref):
        xb = x_ref[...].astype(jnp.float32)
        mean = xb.mean(axis=1, keepdims=True)
        var = jnp.mean(jnp.square(xb - mean), axis=1, keepdims=True)
        y = (xb - mean) * jax.lax.rsqrt(var + eps)
        y = y * s_ref[...].astype(jnp.float32)[None, :] \
            + b_ref[...].astype(jnp.float32)[None, :]
        o_ref[...] = y.astype(o_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_n, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
    )(x, scale, bias)
