"""Op-gap closure, batch 2: interpolation, activations, metrics,
proximal optimizers, sequence/LoD utilities, distillation, distributed
id plumbing.

Parity targets (reference paddle/fluid/operators/): interpolate_op.cc
(bilinear_interp/nearest_interp), selu_op.h, l1_norm_op.h, minus_op.cc,
pad_constant_like_op.h, space_to_depth_op.cc,
sequence_ops/sequence_mask_op.h, sequence_expand_as_op.h,
sequence_erase_op.h, hash_op.h, metrics/precision_recall_op.h,
positive_negative_pair_op.h, optimizers/proximal_gd_op.h,
proximal_adagrad_op.h, average_accumulates_op.h, fsp_op.h,
split_lod_tensor_op.cc, merge_lod_tensor_op.cc,
tensor_array_to_tensor_op.cc, rnn_memory_helper_op.cc,
conv_transpose_op.cc (depthwise_conv2d_transpose),
sync_batch_norm_op.cu, detection/mine_hard_examples_op.cc,
distributed_ops/split_ids_op.h, merge_ids_op.h,
split_selected_rows_op.h, ref_by_trainer_id_op.h,
lookup_sparse_table_op.cc, dgc_clip_by_norm_op.h.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


# --------------------------------------------------------------------------
# image interpolation (reference interpolate_op.cc)
# --------------------------------------------------------------------------
def _interp_sizes(ctx, x):
    oh = ctx.attr("out_h", -1)
    ow = ctx.attr("out_w", -1)
    out_size = ctx.input("OutSize")
    if out_size is not None:
        # XLA needs static shapes: OutSize must be a build-time
        # constant var (the common fluid usage passes one)
        raise ValueError(
            "interp ops need static out_h/out_w attrs on TPU (XLA "
            "static shapes); pass out_shape as ints, not a tensor")
    scale = ctx.attr("scale", 0.0)
    if (oh is None or oh <= 0) and scale:
        oh = int(x.shape[2] * scale)
        ow = int(x.shape[3] * scale)
    if oh is None or oh <= 0 or ow is None or ow <= 0:
        raise ValueError(
            "interp op needs out_h/out_w attrs > 0 or a scale attr "
            "(neither was set)")
    return int(oh), int(ow)


@register_op("bilinear_interp")
def bilinear_interp(ctx):
    """reference interpolate_op.cc BilinearInterpolation: NCHW,
    align_corners/align_mode attrs."""
    x = ctx.input("X")
    oh, ow = _interp_sizes(ctx, x)
    n, c, h, w = x.shape
    align = ctx.attr("align_corners", True)
    mode = ctx.attr("align_mode", 1)

    def src_idx(dst, out_dim, in_dim):
        dst = dst.astype(jnp.float32)
        if align:
            ratio = (in_dim - 1) / max(out_dim - 1, 1)
            return dst * ratio
        ratio = in_dim / out_dim
        if mode == 0:
            return jnp.maximum(ratio * (dst + 0.5) - 0.5, 0.0)
        return ratio * dst

    sy = src_idx(jnp.arange(oh), oh, h)
    sx = src_idx(jnp.arange(ow), ow, w)
    y0 = jnp.clip(jnp.floor(sy).astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(sx).astype(jnp.int32), 0, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (sy - y0).astype(x.dtype)[None, None, :, None]
    wx = (sx - x0).astype(x.dtype)[None, None, None, :]
    g = lambda yy, xx: x[:, :, yy[:, None], xx[None, :]]
    out = ((1 - wy) * (1 - wx) * g(y0, x0) + (1 - wy) * wx * g(y0, x1)
           + wy * (1 - wx) * g(y1, x0) + wy * wx * g(y1, x1))
    return out


@register_op("nearest_interp")
def nearest_interp(ctx):
    x = ctx.input("X")
    oh, ow = _interp_sizes(ctx, x)
    n, c, h, w = x.shape
    align = ctx.attr("align_corners", True)
    if align:
        sy = jnp.round(jnp.arange(oh) * (h - 1) / max(oh - 1, 1))
        sx = jnp.round(jnp.arange(ow) * (w - 1) / max(ow - 1, 1))
    else:
        sy = jnp.floor(jnp.arange(oh) * h / oh)
        sx = jnp.floor(jnp.arange(ow) * w / ow)
    sy = jnp.clip(sy, 0, h - 1).astype(jnp.int32)
    sx = jnp.clip(sx, 0, w - 1).astype(jnp.int32)
    return x[:, :, sy[:, None], sx[None, :]]


# --------------------------------------------------------------------------
# activations / small math
# --------------------------------------------------------------------------
@register_op("selu")
def selu(ctx):
    """reference selu_op.h:35: scale * (x if x>0 else alpha*e^x -
    alpha)."""
    x = ctx.input("X")
    alpha = ctx.attr("alpha", 1.6732632423543772)
    scale = ctx.attr("scale", 1.0507009873554805)
    return scale * jnp.where(x > 0, x, alpha * jnp.exp(x) - alpha)


@register_op("l1_norm")
def l1_norm(ctx):
    """reference l1_norm_op.h: scalar sum |x|."""
    return jnp.sum(jnp.abs(ctx.input("X"))).reshape(1)


@register_op("minus")
def minus(ctx):
    return ctx.input("X") - ctx.input("Y")


@register_op("pad_constant_like")
def pad_constant_like(ctx):
    """reference pad_constant_like_op.h: pad Y up to X's shape with
    pad_value."""
    x, y = ctx.input("X"), ctx.input("Y")
    val = ctx.attr("pad_value", 0.0)
    pads = [(0, int(xs - ys)) for xs, ys in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=val)


@register_op("space_to_depth")
def space_to_depth(ctx):
    """reference space_to_depth_op.cc: NCHW blocksize rearrange."""
    x = ctx.input("X")
    bs = ctx.attr("blocksize")
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * bs * bs, h // bs, w // bs)


@register_op("hash", differentiable=False)
def hash_op(ctx):
    """reference hash_op.h (xxhash % mod_by, num_hash rounds): an
    XLA-computable integer mix hash keeps ids on-device (the exact
    xxhash bits differ; the contract -- deterministic bucketing of int
    ids into [0, mod_by) x num_hash -- is preserved). Id space is
    32-bit: the framework runs with jax x64 disabled, so int64 feeds
    are already int32 on device; mod_by must fit int32."""
    num_hash = ctx.attr("num_hash", 1)
    mod_by = ctx.attr("mod_by")
    if mod_by >= 2 ** 31:
        raise ValueError(f"hash: mod_by={mod_by} must fit int32 "
                         f"(x64 is disabled)")
    x = ctx.input("X").astype(jnp.uint32)
    outs = []
    for i in range(num_hash):
        h = x * jnp.uint32(2654435761) + jnp.uint32(
            (0x9E3779B9 * (i + 1)) & 0xFFFFFFFF)
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        outs.append((h % jnp.uint32(mod_by)).astype(jnp.int32))
    return jnp.stack(outs, axis=-2)


@register_op("fsp")
def fsp(ctx):
    """reference fsp_op.h: FSP (flow of solution procedure) matrix for
    distillation: out[b,i,j] = mean_hw x[b,i,h,w] * y[b,j,h,w]."""
    x, y = ctx.input("X"), ctx.input("Y")
    hw = x.shape[2] * x.shape[3]
    return jnp.einsum("bihw,bjhw->bij", x, y) / hw


# --------------------------------------------------------------------------
# metrics (reference metrics/)
# --------------------------------------------------------------------------
@register_op("precision_recall", differentiable=False)
def precision_recall(ctx):
    """reference precision_recall_op.h: per-class macro/micro
    precision/recall/F1 from MaxProbs+Indices (or detections) vs
    Labels, plus accumulated states."""
    idx = ctx.input("Indices").reshape(-1).astype(jnp.int32)
    labels = ctx.input("Labels").reshape(-1).astype(jnp.int32)
    weights = ctx.input("Weights")
    cls = ctx.attr("class_number")
    w = (weights.reshape(-1).astype(jnp.float32)
         if weights is not None else jnp.ones_like(idx, jnp.float32))
    tp = jnp.zeros(cls).at[labels].add(w * (idx == labels))
    pred_cnt = jnp.zeros(cls).at[idx].add(w)
    lab_cnt = jnp.zeros(cls).at[labels].add(w)
    fp = pred_cnt - tp
    fn = lab_cnt - tp
    states = jnp.stack([tp, fp, fn,
                        jnp.zeros_like(tp)], axis=1)  # TP FP FN TN
    acc_in = ctx.input("StatesInfo")
    if acc_in is not None:
        states = states + acc_in.astype(jnp.float32)
    atp, afp, afn = states[:, 0], states[:, 1], states[:, 2]

    def prf(tp_, fp_, fn_):
        prec = jnp.where(tp_ + fp_ > 0, tp_ / (tp_ + fp_ + 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / (tp_ + fn_ + 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0,
                       2 * prec * rec / (prec + rec + 1e-12), 0.0)
        return prec, rec, f1

    # batch metrics
    bp, br, bf = prf(tp, fp, fn)
    macro_b = jnp.stack([bp.mean(), br.mean(), bf.mean()])
    mp, mr, mf = prf(tp.sum(), fp.sum(), fn.sum())
    # accumulated metrics
    ap, ar, af = prf(atp, afp, afn)
    macro_a = jnp.stack([ap.mean(), ar.mean(), af.mean()])
    map_, mar, maf = prf(atp.sum(), afp.sum(), afn.sum())
    return {"BatchMetrics": jnp.concatenate(
                [macro_b, jnp.stack([mp, mr, mf])]),
            "AccumMetrics": jnp.concatenate(
                [macro_a, jnp.stack([map_, mar, maf])]),
            "AccumStatesInfo": states}


@register_op("positive_negative_pair", differentiable=False)
def positive_negative_pair(ctx):
    """reference positive_negative_pair_op.h: within each query id,
    count score-ordered pairs that agree/disagree with label order."""
    score = ctx.input("Score").reshape(-1)
    label = ctx.input("Label").reshape(-1)
    qid = ctx.input("QueryID").reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    upper = jnp.triu(jnp.ones_like(same_q, dtype=bool), 1)
    valid = same_q & upper & (label[:, None] != label[None, :])
    s_diff = score[:, None] - score[None, :]
    l_diff = (label[:, None] - label[None, :]).astype(s_diff.dtype)
    pos = jnp.sum(valid & (s_diff * l_diff > 0)).astype(jnp.float32)
    neg = jnp.sum(valid & (s_diff * l_diff < 0)).astype(jnp.float32)
    neu = jnp.sum(valid & (s_diff == 0)).astype(jnp.float32)
    acc_p = ctx.input("AccumulatePositivePair")
    acc_n = ctx.input("AccumulateNegativePair")
    acc_u = ctx.input("AccumulateNeutralPair")
    if acc_p is not None:
        pos = pos + acc_p.reshape(())
        neg = neg + acc_n.reshape(())
        neu = neu + acc_u.reshape(())
    return {"PositivePair": pos.reshape(1),
            "NegativePair": neg.reshape(1),
            "NeutralPair": neu.reshape(1)}


# --------------------------------------------------------------------------
# proximal optimizers + accumulators (reference optimizers/)
# --------------------------------------------------------------------------
def _proximal(prox_param, lr, l1, l2):
    if l1 > 0:
        return (jnp.sign(prox_param)
                * jnp.maximum(jnp.abs(prox_param) - lr * l1, 0.0)
                / (1.0 + lr * l2))
    return prox_param / (1.0 + lr * l2)


@register_op("proximal_gd", differentiable=False,
             inplace={"ParamOut": "Param"})
def proximal_gd(ctx):
    """reference proximal_gd_op.h:49-58."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    lr = ctx.input("LearningRate").reshape(())
    return {"ParamOut": _proximal(p - lr * g, lr,
                                  ctx.attr("l1", 0.0),
                                  ctx.attr("l2", 0.0))}


@register_op("proximal_adagrad", differentiable=False,
             inplace={"ParamOut": "Param", "MomentOut": "Moment"})
def proximal_adagrad(ctx):
    """reference proximal_adagrad_op.h: adagrad step then the proximal
    shrink."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    m = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(())
    m_out = m + g * g
    eff_lr = lr / jnp.sqrt(m_out)
    return {"ParamOut": _proximal(p - eff_lr * g, eff_lr,
                                  ctx.attr("l1", 0.0),
                                  ctx.attr("l2", 0.0)),
            "MomentOut": m_out}


@register_op("average_accumulates", differentiable=False,
             inplace={"out_sum_1": "in_sum_1", "out_sum_2": "in_sum_2",
                      "out_sum_3": "in_sum_3",
                      "out_num_accumulates": "in_num_accumulates",
                      "out_old_num_accumulates":
                          "in_old_num_accumulates",
                      "out_num_updates": "in_num_updates"})
def average_accumulates(ctx):
    """reference average_accumulates_op.h: the ModelAverage windowed
    triple-sum rotation."""
    param = ctx.input("param")
    s1 = ctx.input("in_sum_1")
    s2 = ctx.input("in_sum_2")
    s3 = ctx.input("in_sum_3")
    na = ctx.input("in_num_accumulates").reshape(()).astype(jnp.int64)
    ona = ctx.input("in_old_num_accumulates").reshape(()).astype(
        jnp.int64)
    nu = ctx.input("in_num_updates").reshape(()).astype(jnp.int64)
    avg_win = ctx.attr("average_window", 0.0)
    max_win = ctx.attr("max_average_window", 10000)
    min_win = ctx.attr("min_average_window", 10000)
    na = na + 1
    nu = nu + 1
    s1 = s1 + param
    # reference average_accumulates_op.h:94-104: rotate when
    # num_acc >= min_window AND num_acc >= min(max_window,
    # num_updates * average_window); the old window (sums 1+2+3)
    # moves wholesale into sum_3 and restarts
    thresh = jnp.minimum(
        jnp.asarray(max_win, jnp.float32),
        nu.astype(jnp.float32) * avg_win)
    rotate = (na >= min_win) & (na.astype(jnp.float32) >= thresh)
    # sum_3 REPLACED by the window being discarded (in-place aliasing
    # in the reference means sum_1 already includes this step's param)
    s3r = jnp.where(rotate, s1 + s2, s3)
    s1r = jnp.where(rotate, jnp.zeros_like(s1), s1)
    s2f = jnp.where(rotate, jnp.zeros_like(s2), s2)
    onar = jnp.where(rotate, na, ona)
    naf = jnp.where(rotate, jnp.zeros_like(na), na)
    return {"out_sum_1": s1r, "out_sum_2": s2f, "out_sum_3": s3r,
            "out_num_accumulates": naf.reshape(1),
            "out_old_num_accumulates": onar.reshape(1),
            "out_num_updates": nu.reshape(1)}


@register_op("dgc_clip_by_norm", differentiable=False)
def dgc_clip_by_norm(ctx):
    """reference dgc_clip_by_norm_op.h: clip_by_norm applied after
    rampup_begin_step (before that, pass through)."""
    x = ctx.input("X")
    step = ctx.input("current_step")
    begin = ctx.attr("rampup_begin_step", 0.0)
    maxn = ctx.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(x * x))
    clipped = jnp.where(norm > maxn, x * (maxn / norm), x)
    if step is None:
        return clipped
    return jnp.where(step.reshape(()) < begin, x, clipped)


# --------------------------------------------------------------------------
# sequence / LoD utilities (padded + @SEQ_LEN design)
# --------------------------------------------------------------------------
@register_op("sequence_mask", differentiable=False)
def sequence_mask(ctx):
    """reference sequence_mask_op.h: Y[..., j] = j < X[...]."""
    x = ctx.input("X").astype(jnp.int32)
    maxlen = ctx.attr("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError(
            "sequence_mask needs a static maxlen attr on TPU (XLA "
            "static shapes); maxlen=-1 (max of X) is data-dependent")
    out_dtype = ctx.attr("out_dtype", 5)
    from ..core.types import DataType, to_jnp_dtype

    dt = to_jnp_dtype(DataType(out_dtype)) if not isinstance(
        out_dtype, str) else jnp.dtype(out_dtype)
    j = jnp.arange(maxlen, dtype=jnp.int32)
    return (j < x[..., None]).astype(dt)


@register_op("sequence_expand_as", stop_gradient_slots=("Y",))
def sequence_expand_as(ctx):
    """reference sequence_expand_as_op.h: repeat each row of X to its
    matching Y sequence length. Padded form: X [B, ...] broadcast over
    Y's time axis [B, T, ...]; rows beyond @SEQ_LEN are zeros."""
    x = ctx.input("X")
    y = ctx.input("Y")
    seq_len = ctx.input("SeqLen")
    t = y.shape[1]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], t) + x.shape[1:])
    if seq_len is not None:
        mask = (jnp.arange(t)[None, :] < seq_len[:, None]).astype(
            out.dtype)
        out = out * mask.reshape(mask.shape + (1,) * (out.ndim - 2))
    return out


@register_op("sequence_erase", differentiable=False)
def sequence_erase(ctx):
    """reference sequence_erase_op.h: drop the listed tokens from each
    sequence, compacting left. Padded form: stable left-shift of the
    kept tokens, zero pad, @SEQ_LEN shrinks accordingly (returned as
    OutLen)."""
    x = ctx.input("X")  # [B, T] int
    seq_len = ctx.input("SeqLen")
    tokens = jnp.asarray(ctx.attr("tokens", []), x.dtype)
    t = x.shape[1]
    pos = jnp.arange(t)[None, :]
    valid = (pos < seq_len[:, None]) if seq_len is not None else \
        jnp.ones_like(x, bool)
    keep = valid & ~jnp.isin(x, tokens)
    # stable compaction: kept tokens get rank = cumsum-1, dropped go
    # past the end and fall off via mode="drop"
    rank = jnp.cumsum(keep, axis=1) - 1
    dest = jnp.where(keep, rank, t)
    out = jnp.zeros_like(x)
    rows = jnp.arange(x.shape[0])[:, None]
    out = out.at[rows, dest].set(jnp.where(keep, x, 0), mode="drop")
    return {"Out": out, "OutLen": keep.sum(axis=1).astype(jnp.int32)}


@register_op("split_lod_tensor", differentiable=False)
def split_lod_tensor(ctx):
    """reference split_lod_tensor_op.cc (the IfElse splitter): rows
    routed by Mask. Static-shape form: both outputs keep the full
    batch, rows not belonging are zeroed; the ifelse op composes the
    true/false flows row-wise (ops/lod_ops.py)."""
    x = ctx.input("X")
    mask = ctx.input("Mask").reshape(-1).astype(bool)
    m = mask.reshape((-1,) + (1,) * (x.ndim - 1))
    return {"OutTrue": jnp.where(m, x, 0),
            "OutFalse": jnp.where(m, 0, x)}


@register_op("merge_lod_tensor", differentiable=False)
def merge_lod_tensor(ctx):
    """reference merge_lod_tensor_op.cc: inverse of split_lod_tensor
    under the zero-fill convention."""
    mask = ctx.input("Mask").reshape(-1).astype(bool)
    t, f = ctx.input("InTrue"), ctx.input("InFalse")
    m = mask.reshape((-1,) + (1,) * (t.ndim - 1))
    return jnp.where(m, t, f)


@register_op("tensor_array_to_tensor", differentiable=False,
             infer_shape=lambda op, block: None)
def tensor_array_to_tensor(ctx):
    """reference tensor_array_to_tensor_op.cc: stack/concat the array
    entries along attr axis."""
    vals = [v for v in ctx.inputs("X") if v is not None]
    axis = ctx.attr("axis", 0)
    use_stack = ctx.attr("use_stack", False)
    if not ctx.attr("from_list", False) and len(vals) == 1:
        # single input that IS a stacked array-var: its leading dim
        # enumerates the array entries. The layer sets from_list=True
        # when X is a python list of vars, which is the only way to
        # tell a one-element array apart from a stacked var.
        vals = list(vals[0])
    out = (jnp.stack(vals, axis=axis) if use_stack
           else jnp.concatenate(vals, axis=axis))
    idx = jnp.asarray([v.shape[axis] if not use_stack else 1
                       for v in vals], jnp.int32)
    return {"Out": out, "OutIndex": idx}


@register_op("rnn_memory_helper")
def rnn_memory_helper(ctx):
    """reference rnn_memory_helper_op.cc: identity used by StaticRNN's
    step_output plumbing (kept for program-level parity)."""
    return ctx.input("X")


# --------------------------------------------------------------------------
# conv variants / norm
# --------------------------------------------------------------------------
@register_op("depthwise_conv2d_transpose")
def depthwise_conv2d_transpose(ctx):
    """reference conv_transpose_op.cc depthwise variant: groups ==
    channels transpose conv."""
    from .nn_ops import _conv_transpose_nd, _pair

    x = ctx.input("Input")
    w = ctx.input("Filter")
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    dilations = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", x.shape[1])
    return {"Output": _conv_transpose_nd(x, w, strides, pads,
                                         dilations, groups, spatial=2)}


@register_op("sync_batch_norm", grad_maker=None)
def sync_batch_norm(ctx):
    """reference sync_batch_norm_op.cu: batch norm with CROSS-REPLICA
    statistics. Under the GSPMD executor the whole batch is one logical
    tensor, so plain batch_norm stats are already global -- this alias
    documents that and additionally psums over an explicit shard_map
    axis when one is active (attr axis_name)."""
    from .nn_ops import batch_norm

    axis = ctx.attr("axis_name", None)
    if axis is None:
        return batch_norm(ctx)
    x = ctx.input("X")
    scale = ctx.input("Scale")
    bias = ctx.input("Bias")
    mean_in = ctx.input("Mean")
    var_in = ctx.input("Variance")
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    red = tuple(i for i in range(x.ndim) if i != 1)
    n_local = np.prod([x.shape[i] for i in red])
    s = lax.psum(jnp.sum(x, axis=red), axis)
    ss = lax.psum(jnp.sum(x * x, axis=red), axis)
    n = lax.psum(jnp.asarray(float(n_local)), axis)
    mean = s / n
    var = ss / n - mean * mean
    inv_std = jax.lax.rsqrt(var + eps)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    y = (x - mean.reshape(bshape)) * inv_std.reshape(bshape) \
        * scale.reshape(bshape) + bias.reshape(bshape)
    # same contract as nn_ops.batch_norm: SavedVariance holds inv-std
    # (cuDNN convention) and the running stats get the momentum blend
    mean_out = (mean_in * momentum + mean * (1 - momentum)
                if mean_in is not None else mean)
    var_out = (var_in * momentum + var * (1 - momentum)
               if var_in is not None else var)
    return {"Y": y, "SavedMean": mean, "SavedVariance": inv_std,
            "MeanOut": mean_out, "VarianceOut": var_out}


# --------------------------------------------------------------------------
# distributed id plumbing (reference distributed_ops/)
# --------------------------------------------------------------------------
@register_op("split_ids", differentiable=False)
def split_ids(ctx):
    """reference split_ids_op.h: mod-shard ids across N outputs.
    Static-shape form: each shard keeps the input length; slots not
    belonging to the shard hold -1 padding."""
    ids = ctx.input("Ids")
    n = len(ctx.op.outputs["Out"])
    outs = []
    for i in range(n):
        mine = (ids % n) == i
        outs.append(jnp.where(mine, ids // n, -1))
    return {"Out": outs}


@register_op("merge_ids", differentiable=False)
def merge_ids(ctx):
    """reference merge_ids_op.h: route per-shard embedding rows back
    to the original id order (inverse of split_ids + prefetch)."""
    ids = ctx.input("Ids")  # original ids [N]
    shard_vals = ctx.inputs("X")  # per-shard [N, D] rows (padded)
    n = len(shard_vals)
    out = jnp.zeros_like(shard_vals[0])
    for i, sv in enumerate(shard_vals):
        mine = ((ids % n) == i).reshape(-1, 1)
        out = jnp.where(mine, sv, out)
    return {"Out": out}


@register_op("split_selected_rows", differentiable=False)
def split_selected_rows(ctx):
    """reference split_selected_rows_op.h: partition (rows, values) by
    height_sections; pad slots -1."""
    rows = ctx.input("Rows")
    vals = ctx.input("Values")
    sections = list(ctx.attr("height_sections"))
    outs_r, outs_v = [], []
    start = 0
    for sec in sections:
        mine = (rows >= start) & (rows < start + sec)
        outs_r.append(jnp.where(mine, rows - start, -1))
        outs_v.append(jnp.where(mine.reshape(-1, 1), vals, 0))
        start += sec
    return {"OutRows": outs_r, "OutValues": outs_v}


@register_op("lookup_sparse_table", differentiable=False)
def lookup_sparse_table(ctx):
    """reference lookup_sparse_table_op.cc: embedding lookup that
    auto-grows unknown ids (pserver-side). Single-program form: plain
    gather with padding ids clamped (growth happens in the pserver
    runtime's push_sparse_grad path)."""
    w = ctx.input("W")
    ids = ctx.input("Ids").astype(jnp.int32)
    safe = jnp.clip(ids, 0, w.shape[0] - 1)
    out = w[safe.reshape(-1)]
    out = jnp.where((ids.reshape(-1) >= 0)[:, None], out, 0)
    return out.reshape(tuple(ids.shape) + (w.shape[1],))


@register_op("ref_by_trainer_id", differentiable=False)
def ref_by_trainer_id(ctx):
    """reference ref_by_trainer_id_op.h: select X[trainer_id]."""
    xs = ctx.inputs("X")
    tid = ctx.input("TrainerId")
    i = jnp.reshape(tid, ()).astype(jnp.int32)
    stacked = jnp.stack(xs)
    return stacked[i]


# --------------------------------------------------------------------------
# detection extra
# --------------------------------------------------------------------------
@register_op("mine_hard_examples", differentiable=False)
def mine_hard_examples(ctx):
    """reference detection/mine_hard_examples_op.cc: pick the hardest
    negatives per image at neg_pos_ratio. Padded form: NegIndices is
    [B, M] with -1 padding; UpdatedMatchIndices keeps positives."""
    cls_loss = ctx.input("ClsLoss")  # [B, M]
    match = ctx.input("MatchIndices")  # [B, M]
    neg_pos_ratio = ctx.attr("neg_pos_ratio", 3.0)
    neg_overlap = ctx.attr("neg_dist_threshold", 0.5)
    dist = ctx.input("MatchDist")
    b, m = cls_loss.shape
    pos = match >= 0
    n_pos = pos.sum(axis=1)
    n_neg = jnp.minimum((neg_pos_ratio * n_pos).astype(jnp.int32), m)
    cand = (~pos)
    if dist is not None:
        cand = cand & (dist < neg_overlap)
    score = jnp.where(cand, cls_loss, -jnp.inf)
    order = jnp.argsort(-score, axis=1)
    rank = jnp.arange(m)[None, :]
    chosen = rank < n_neg[:, None]
    has_cand = jnp.take_along_axis(score, order, axis=1) > -jnp.inf
    neg_idx = jnp.where(chosen & has_cand, order, -1)
    return {"NegIndices": neg_idx.astype(jnp.int32),
            "UpdatedMatchIndices": match}
