"""Sampled / structured losses: CTC, linear-chain CRF (+viterbi), NCE,
hierarchical sigmoid, sampled logits.

TPU-native counterparts of the reference ops (reference
operators/warpctc_op.cc — binds the external warp-ctc library —
linear_chain_crf_op.cc/.h, crf_decoding_op.cc, nce_op.cc,
hierarchical_sigmoid_op.cc, sample_logits_op.cc). The reference computes
these on host/CUDA with hand-written gradients; here each forward is a
pure lax.scan/jnp composition over padded dense batches, and gradients
fall out of jax.vjp through the scan (no hand-written backward).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

_NEG = -1e30


@register_op("warpctc", stop_gradient_slots=("Label", "LogitsLen",
                                             "LabelLen"))
def warpctc(ctx):
    """CTC loss via the log-space alpha recursion (replaces the warp-ctc
    external binding, reference warpctc_op.cc).

    inputs: Logits [B, T, C] raw (softmax applied inside, matching
    fluid's norm_by_times-free default), Label [B, L] int (no blanks),
    LogitsLen [B], LabelLen [B]. attr: blank (default 0).
    outputs: Loss [B, 1] = -log p(label | logits).
    """
    logits = ctx.input("Logits")
    label = ctx.input("Label")
    b, t, c = logits.shape
    l = label.shape[1]
    blank = int(ctx.attr("blank", 0))
    tlen = ctx.input("LogitsLen")
    llen = ctx.input("LabelLen")
    tlen = (jnp.full((b,), t, jnp.int32) if tlen is None
            else tlen.reshape(b).astype(jnp.int32))
    llen = (jnp.full((b,), l, jnp.int32) if llen is None
            else llen.reshape(b).astype(jnp.int32))

    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    s = 2 * l + 1
    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((b, s), blank, label.dtype)
    ext = ext.at[:, 1::2].set(label)
    ext_valid = jnp.arange(s)[None, :] < (2 * llen + 1)[:, None]

    # allowed skip: alpha[s] can come from s-2 when ext[s] != blank and
    # ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate(
        [jnp.full((b, 2), -1, ext.dtype), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_prev2)

    def emit(t_idx):
        return jnp.take_along_axis(logp[:, t_idx, :], ext.astype(jnp.int32),
                                   axis=1)  # [B, S]

    alpha0 = jnp.full((b, s), _NEG, jnp.float32)
    alpha0 = alpha0.at[:, 0].set(emit(0)[:, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(llen > 0, emit(0)[:, 1], _NEG))

    def step(alpha, t_idx):
        shifted1 = jnp.concatenate(
            [jnp.full((b, 1), _NEG), alpha[:, :-1]], axis=1)
        shifted2 = jnp.concatenate(
            [jnp.full((b, 2), _NEG), alpha[:, :-2]], axis=1)
        stay = jnp.logaddexp(alpha, shifted1)
        new = jnp.where(can_skip, jnp.logaddexp(stay, shifted2), stay)
        new = new + emit(t_idx)
        new = jnp.where(ext_valid, new, _NEG)
        # frames beyond this row's length keep old alpha
        active = (t_idx < tlen)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, t))
    last = 2 * llen  # index of final blank in ext
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    a_prev = jnp.where(llen > 0, a_prev, _NEG)
    loss = -jnp.logaddexp(a_last, a_prev)
    return {"Loss": loss.reshape(b, 1)}


@register_op("linear_chain_crf",
             stop_gradient_slots=("Label", "Length"))
def linear_chain_crf(ctx):
    """Negative log-likelihood of a linear-chain CRF (reference
    linear_chain_crf_op.h — same parameterization: Transition row 0 =
    start weights, row 1 = end weights, rows 2.. = [C, C] transitions).

    inputs: Emission [B, T, C], Transition [C+2, C], Label [B, T] int,
    Length [B] (optional). outputs: LogLikelihood [B, 1] (negative NLL,
    i.e. log p — matching fluid, which returns the log-likelihood and
    trains on its negation via mean+scale), Alpha [B, T, C].
    """
    em = ctx.input("Emission").astype(jnp.float32)
    trans = ctx.input("Transition").astype(jnp.float32)
    label = ctx.input("Label")
    b, t, c = em.shape
    length = ctx.input("Length")
    length = (jnp.full((b,), t, jnp.int32) if length is None
              else length.reshape(b).astype(jnp.int32))
    label = label.reshape(b, t).astype(jnp.int32)
    start_w, end_w, pair = trans[0], trans[1], trans[2:]

    # partition function via forward algorithm
    alpha0 = start_w[None, :] + em[:, 0, :]

    def step(alpha, t_idx):
        # [B, C_prev, 1] + [C_prev, C] -> logsumexp over prev
        scores = alpha[:, :, None] + pair[None, :, :]
        new = jax.scipy.special.logsumexp(scores, axis=1) + em[:, t_idx, :]
        active = (t_idx < length)[:, None]
        return jnp.where(active, new, alpha), jnp.where(active, new, alpha)

    alpha_last, alphas = lax.scan(step, alpha0, jnp.arange(1, t))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, C]
    logz = jax.scipy.special.logsumexp(alpha_last + end_w[None, :], axis=1)

    # gold path score
    pos = jnp.arange(t)
    valid = pos[None, :] < length[:, None]
    em_score = jnp.take_along_axis(em, label[:, :, None],
                                   axis=2)[:, :, 0]
    em_score = jnp.sum(jnp.where(valid, em_score, 0.0), axis=1)
    prev_lab = label[:, :-1]
    next_lab = label[:, 1:]
    pair_score = pair[prev_lab, next_lab]  # [B, T-1]
    pair_valid = pos[None, 1:] < length[:, None]
    pair_score = jnp.sum(jnp.where(pair_valid, pair_score, 0.0), axis=1)
    last_lab = jnp.take_along_axis(
        label, jnp.maximum(length - 1, 0)[:, None], axis=1)[:, 0]
    path = (start_w[label[:, 0]] + em_score + pair_score +
            end_w[last_lab])
    ll = path - logz
    return {"LogLikelihood": -ll.reshape(b, 1),
            "Alpha": jnp.transpose(alphas, (1, 0, 2))}


@register_op("crf_decoding", differentiable=False,
             stop_gradient_slots=("Emission", "Transition", "Label",
                                  "Length"))
def crf_decoding(ctx):
    """Viterbi decode (reference crf_decoding_op.h). outputs
    ViterbiPath [B, T] int64 (0 beyond length); with a Label input,
    outputs the per-position correctness indicator instead (fluid
    semantics)."""
    em = ctx.input("Emission").astype(jnp.float32)
    trans = ctx.input("Transition").astype(jnp.float32)
    b, t, c = em.shape
    length = ctx.input("Length")
    length = (jnp.full((b,), t, jnp.int32) if length is None
              else length.reshape(b).astype(jnp.int32))
    start_w, end_w, pair = trans[0], trans[1], trans[2:]

    v0 = start_w[None, :] + em[:, 0, :]

    def fwd(v, t_idx):
        scores = v[:, :, None] + pair[None, :, :]       # [B, Cp, C]
        best_prev = jnp.argmax(scores, axis=1)          # [B, C]
        new = jnp.max(scores, axis=1) + em[:, t_idx, :]
        active = (t_idx < length)[:, None]
        new = jnp.where(active, new, v)
        best_prev = jnp.where(
            active, best_prev,
            jnp.broadcast_to(jnp.arange(c)[None, :], (b, c)))
        return new, best_prev

    v_last, backptrs = lax.scan(fwd, v0, jnp.arange(1, t))  # [T-1, B, C]
    last_tag = jnp.argmax(v_last + end_w[None, :], axis=1)  # [B]

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    tag0, tags_rev = lax.scan(back, last_tag, backptrs[::-1])
    # tags_rev = [tag_{T-1} .. tag_1]; the final carry is tag_0
    path = jnp.concatenate(
        [tag0[None], tags_rev[::-1]], axis=0).T         # [B, T]
    valid = jnp.arange(t)[None, :] < length[:, None]
    path = jnp.where(valid, path, 0).astype(jnp.int64)
    label = ctx.input("Label")
    if label is not None:
        correct = (path == label.reshape(b, t).astype(jnp.int64))
        return {"ViterbiPath": jnp.where(valid, correct, 0
                                         ).astype(jnp.int64)}
    return {"ViterbiPath": path}


@register_op("nce", needs_rng=True,
             stop_gradient_slots=("Label", "SampleWeight"))
def nce(ctx):
    """Noise-contrastive estimation loss (reference nce_op.h — uniform
    sampler default). Nonzero `seed` attr pins the noise samples
    (reference deterministic mode); seed=0 draws fresh noise per step
    from the executor key chain (ctx.rng() is stable within one step's
    fwd/vjp recomputation, varying across steps).

    inputs: Input [B, D], Label [B, num_true], Weight [V, D], Bias [V].
    attrs: num_neg_samples, num_total_classes, seed.
    outputs: Cost [B, 1], plus SampleLogits/SampleLabels for parity.
    """
    x = ctx.input("Input")
    label = ctx.input("Label")
    w = ctx.input("Weight")
    bias = ctx.input("Bias")
    b, d = x.shape
    v = int(ctx.attr("num_total_classes", w.shape[0]))
    num_neg = int(ctx.attr("num_neg_samples", 10))
    seed = int(ctx.attr("seed", 0))
    label = label.reshape(b, -1).astype(jnp.int32)
    nt = label.shape[1]

    key = jax.random.PRNGKey(seed) if seed else ctx.rng()
    noise = jax.random.randint(key, (b, num_neg), 0, v)   # [B, S]
    samples = jnp.concatenate([label, noise], axis=1)     # [B, nt+S]
    sw = w[samples]                                       # [B, nt+S, D]
    logits = jnp.einsum("bd,bsd->bs", x, sw)
    if bias is not None:
        logits = logits + bias.reshape(-1)[samples]
    # uniform sampler: q = 1/V for every class
    logq = -math.log(v)
    adj = logits - (logq + math.log(max(num_neg, 1)))
    targets = jnp.concatenate(
        [jnp.ones((b, nt)), jnp.zeros((b, num_neg))], axis=1)
    per = (jax.nn.softplus(adj) - targets * adj)
    cost = jnp.sum(per, axis=1, keepdims=True) / nt
    return {"Cost": cost, "SampleLogits": logits,
            "SampleLabels": samples.astype(jnp.int64)}


@register_op("hierarchical_sigmoid", stop_gradient_slots=("Label",))
def hierarchical_sigmoid(ctx):
    """Hierarchical softmax over the default complete binary tree
    (reference hierarchical_sigmoid_op.h, matrix_bit_code.h — same
    node/code derivation: leaf id = label + V - 1, ancestors by
    (i-1)//2, code bit = is-right-child).

    inputs: X [B, D], W [V-1, D], Label [B, 1], Bias [V-1] optional.
    attr: num_classes. outputs: Out [B, 1] loss, PreOut [B, depth].
    """
    x = ctx.input("X")
    w = ctx.input("W")
    label = ctx.input("Label")
    bias = ctx.input("Bias")
    b, d = x.shape
    v = int(ctx.attr("num_classes"))
    depth = max(1, math.ceil(math.log2(max(v, 2)))) + 1  # masked slack
    lab = label.reshape(b).astype(jnp.int32)

    node = lab + (v - 1)          # leaf index in the implicit full tree
    node_ids, codes, masks = [], [], []
    for _ in range(depth):
        parent = (node - 1) // 2
        is_right = (node % 2 == 0)        # right child has even index
        valid = node > 0
        node_ids.append(jnp.where(valid, parent, 0))
        codes.append(jnp.where(valid, is_right, False))
        masks.append(valid)
        node = jnp.where(valid, parent, node)
    nid = jnp.stack(node_ids, axis=1)     # [B, depth]
    code = jnp.stack(codes, axis=1).astype(x.dtype)
    mask = jnp.stack(masks, axis=1).astype(x.dtype)

    wn = w[nid]                           # [B, depth, D]
    pre = jnp.einsum("bd,bkd->bk", x, wn)
    if bias is not None:
        pre = pre + bias.reshape(-1)[nid]
    # BCE with target = code
    per = jax.nn.softplus(pre) - code * pre
    loss = jnp.sum(per * mask, axis=1, keepdims=True)
    return {"Out": loss, "PreOut": pre}


@register_op("sample_logits", needs_rng=True,
             stop_gradient_slots=("Labels",))
def sample_logits(ctx):
    """Sampled-softmax helper (reference sample_logits_op.cc): gather
    logits at true + uniformly sampled classes with log-Q correction.

    inputs: Logits [B, C], Labels [B, num_true]. attrs: num_samples,
    seed, remove_accidental_hits. outputs: SampledLogits
    [B, nt+num_samples], SampledLabels [B, nt] (positions of true
    classes in the sampled axis), Samples, Probabilities.
    """
    logits = ctx.input("Logits")
    labels = ctx.input("Labels").astype(jnp.int32)
    b, c = logits.shape
    ns = int(ctx.attr("num_samples", 10))
    seed = int(ctx.attr("seed", 0))
    labels = labels.reshape(b, -1)
    nt = labels.shape[1]
    key = jax.random.PRNGKey(seed) if seed else ctx.rng()
    sampled = jax.random.randint(key, (b, ns), 0, c)
    samples = jnp.concatenate([labels, sampled], axis=1)
    gathered = jnp.take_along_axis(logits, samples, axis=1)
    q = jnp.full((b, nt + ns), 1.0 / c, logits.dtype)
    out = gathered - jnp.log(q)  # logQ correction: logits - log q(y)
    if ctx.attr("remove_accidental_hits", True):
        # a sampled class equal to a true label gets masked out
        hit = (sampled[:, None, :] == labels[:, :, None]).any(axis=1)
        pad = jnp.concatenate(
            [jnp.zeros((b, nt), bool), hit], axis=1)
        out = jnp.where(pad, _NEG, out)
    # softmax CE over the sampled axis, true classes at positions [:nt]
    logz = jax.scipy.special.logsumexp(out, axis=1, keepdims=True)
    loss = logz - out[:, :nt].sum(axis=1, keepdims=True) / nt \
        if nt > 1 else logz - out[:, :1]
    return {"Loss": loss,
            "SampledLogits": out,
            "SampledLabels": jnp.broadcast_to(
                jnp.arange(nt)[None, :], (b, nt)).astype(jnp.int64),
            "Samples": samples.astype(jnp.int64),
            "Probabilities": q}
