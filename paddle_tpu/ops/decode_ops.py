"""Decoding ops: beam search, beam-search decode, edit distance,
ctc alignment.

TPU-native counterparts of the reference's decode machinery (reference
operators/beam_search_op.cc, beam_search_decode_op.cc, ctc_align_op.cc,
edit_distance_op.cc, math/beam_search.cc). The reference represents beams
via LoD offsets mutated on the host between steps; here everything is
static-shape device math — beams are a dense [batch, beam] axis, parents
are explicit index tensors, and the backtrack is a lax.scan — so whole
decode loops compile into one XLA program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


@register_op("beam_search", differentiable=False,
             stop_gradient_slots=("pre_ids", "ids"))
def beam_search(ctx):
    """One beam-search step over dense [batch*beam, K] candidates.

    inputs: pre_ids [B*beam, 1] int64 (last selected ids), pre_scores
    [B*beam, 1] f32 (cumulative log-probs), ids [B*beam, K] int64
    (top-K candidate token ids per beam), scores [B*beam, K] f32
    (their log-probs). attrs: beam_size, end_id.
    outputs: selected_ids [B*beam, 1], selected_scores [B*beam, 1],
    parent_idx [B*beam] int32 (which source beam each selection extends,
    absolute row index — the fluid 1.4 op encodes this via LoD; the
    explicit tensor is the static-shape equivalent).

    Finished beams (pre_id == end_id) are frozen: their only candidate is
    end_id with unchanged score (reference math/beam_search.cc same rule).
    """
    pre_ids = ctx.input("pre_ids")
    pre_scores = ctx.input("pre_scores")
    ids = ctx.input("ids")
    scores = ctx.input("scores")
    beam = int(ctx.attr("beam_size"))
    end_id = int(ctx.attr("end_id", 0))
    # reference beam_search_op.cc is_accumulated semantics: True (the
    # layer default) means `scores` ALREADY hold the full accumulated
    # log-prob per candidate; False means `scores` are raw per-step
    # probabilities and the op accumulates pre + log(p) itself.
    # (Previously this attr was ignored and pre_scores always added —
    # double-counting history for every accumulated-score caller.)
    is_accumulated = bool(ctx.attr("is_accumulated", True))

    rows = ids.shape[0]
    k = ids.shape[1]
    b = rows // beam
    finished = (pre_ids.reshape(rows) == end_id)

    if is_accumulated:
        total = scores  # [rows, K]
    else:
        total = pre_scores.reshape(rows, 1) + \
            jnp.log(jnp.maximum(scores, 1e-30))
    neg = jnp.finfo(total.dtype).min
    # frozen beams: candidate 0 = end_id @ pre_score, others impossible
    frozen_scores = jnp.concatenate(
        [pre_scores.reshape(rows, 1),
         jnp.full((rows, k - 1), neg, total.dtype)], axis=1)
    frozen_ids = jnp.full((rows, k), end_id, ids.dtype)
    total = jnp.where(finished[:, None], frozen_scores, total)
    cand_ids = jnp.where(finished[:, None], frozen_ids, ids)

    # per batch: pick top beam among beam*K candidates
    total_b = total.reshape(b, beam * k)
    ids_b = cand_ids.reshape(b, beam * k)
    top_scores, top_pos = lax.top_k(total_b, beam)      # [b, beam]
    sel_ids = jnp.take_along_axis(ids_b, top_pos, axis=1)
    src_beam = top_pos // k                             # [b, beam]
    parent = (src_beam +
              jnp.arange(b, dtype=src_beam.dtype)[:, None] * beam)

    return {"selected_ids": sel_ids.reshape(rows, 1),
            "selected_scores": top_scores.reshape(rows, 1),
            "parent_idx": parent.reshape(rows).astype(jnp.int32)}


@register_op("beam_search_decode", differentiable=False,
             stop_gradient_slots=("Ids", "Parents"))
def beam_search_decode(ctx):
    """Backtrack stacked per-step selections into full sequences.

    inputs: Ids — tensor array (or stacked [T, B*beam, 1]) of selected
    ids; Parents — same-shaped parent_idx per step; Scores (optional) —
    per-step cumulative scores. attrs: beam_size, end_id.
    outputs: SentenceIds [T, B*beam] int64 (backtracked token per step),
    SentenceScores [B*beam] f32 (final cumulative score of each beam).
    Reference beam_search_decode_op.cc builds LoDTensor sentences on
    host; the static-shape output pads finished rows with end_id.
    """
    ids = ctx.input("Ids")
    parents = ctx.input("Parents")
    scores = ctx.input("Scores")
    if isinstance(ids, list):
        ids = jnp.stack(list(ids))
    if isinstance(parents, list):
        parents = jnp.stack(list(parents))
    if isinstance(scores, list):
        scores = jnp.stack(list(scores))
    t = ids.shape[0]
    ids2 = ids.reshape(t, -1)          # [T, rows]
    rows = ids2.shape[1]
    if parents is None:
        # no lineage: each beam is its own ancestor
        parents = jnp.broadcast_to(
            jnp.arange(rows, dtype=jnp.int32)[None, :], (t, rows))
    par2 = parents.reshape(t, -1).astype(jnp.int32)

    # backward scan: carry = beam assignment at step s+1
    def step(carry, xs):
        step_ids, step_par = xs
        tok = step_ids[carry]
        carry_prev = step_par[carry]
        return carry_prev, tok

    init = jnp.arange(rows, dtype=jnp.int32)
    _, toks = lax.scan(step, init, (ids2[::-1], par2[::-1]))
    sentence = toks[::-1]              # [T, rows]
    if scores is None:
        final_scores = jnp.zeros((rows,), jnp.float32)
    elif scores.shape[0] == t and scores.size == t * rows:
        final_scores = scores.reshape(t, -1)[-1]  # per-step stack
    else:
        final_scores = scores.reshape(-1)         # already final [rows]
    return {"SentenceIds": sentence.astype(jnp.int64),
            "SentenceScores": final_scores}


@register_op("edit_distance", differentiable=False,
             stop_gradient_slots=("Hyps", "Refs", "HypsLen", "RefsLen"))
def edit_distance(ctx):
    """Batched Levenshtein distance over padded int sequences.

    inputs: Hyps [B, Th], Refs [B, Tr] int64 (padded), HypsLen/RefsLen
    [B] actual lengths (optional; default full width). attr: normalized.
    outputs: Out [B, 1] f32 distances, SequenceNum [1] int64.
    Reference edit_distance_op.cc runs the same DP per LoD sequence on
    host/CUDA; here one lax.scan over ref positions updates all batch
    rows' DP columns in parallel (vectorized over B and Th).
    """
    hyps = ctx.input("Hyps")
    refs = ctx.input("Refs")
    b, th = hyps.shape[0], hyps.shape[1]
    tr = refs.shape[1]
    hlen = ctx.input("HypsLen")
    rlen = ctx.input("RefsLen")
    if hlen is None:
        hlen = jnp.full((b,), th, jnp.int32)
    if rlen is None:
        rlen = jnp.full((b,), tr, jnp.int32)
    hlen = hlen.reshape(b).astype(jnp.int32)
    rlen = rlen.reshape(b).astype(jnp.int32)

    # DP over ref prefix length i: row[j] = dist(ref[:i], hyp[:j]).
    # Positions j > hlen are clamped by masking at the end; interior
    # cells beyond length are computed but unused.
    j_idx = jnp.arange(th + 1)
    row0 = jnp.broadcast_to(j_idx.astype(jnp.float32),
                            (b, th + 1))  # dist(ref[:0], hyp[:j]) = j

    def step(row, i):
        ref_tok = refs[:, i]                              # [B]
        sub_cost = (hyps != ref_tok[:, None]).astype(jnp.float32)
        base = jnp.full((b,), jnp.float32(i + 1))

        def inner(carry, j):
            # carry = new_row[j-1]; row is the previous DP row (closure)
            delete = row[:, j] + 1.0
            insert = carry + 1.0
            substitute = row[:, j - 1] + sub_cost[:, j - 1]
            val = jnp.minimum(jnp.minimum(delete, insert), substitute)
            return val, val

        _, inner_vals = lax.scan(inner, base, jnp.arange(1, th + 1))
        new_row = jnp.concatenate([base[:, None], inner_vals.T], axis=1)
        # rows whose ref is shorter than i+1 keep their old DP row
        active = (i < rlen)[:, None]
        new_row = jnp.where(active, new_row, row)
        return new_row, None

    final_row, _ = lax.scan(step, row0, jnp.arange(tr))
    dist = jnp.take_along_axis(final_row, hlen[:, None], axis=1)[:, 0]
    if ctx.attr("normalized", False):
        dist = dist / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    return {"Out": dist.reshape(b, 1),
            "SequenceNum": jnp.asarray([b], jnp.int64)}


@register_op("ctc_align", differentiable=False,
             stop_gradient_slots=("Input", "InputLen"))
def ctc_align(ctx):
    """CTC post-alignment: merge repeats, drop blanks (reference
    ctc_align_op.cc). inputs: Input [B, T] int (argmax path), optional
    InputLen [B]. attr: blank. outputs: Output [B, T] with the merged
    tokens left-aligned and `blank`-padded, OutputLen [B].
    """
    x = ctx.input("Input")
    b, t = x.shape[0], x.shape[1]
    blank = int(ctx.attr("blank", 0))
    xlen = ctx.input("InputLen")
    if xlen is None:
        xlen = ctx.input("SeqLen")
    if xlen is None:
        xlen = jnp.full((b,), t, jnp.int32)
    xlen = xlen.reshape(b).astype(jnp.int32)

    pos_idx = jnp.arange(t)
    valid = pos_idx[None, :] < xlen[:, None]
    prev = jnp.concatenate(
        [jnp.full((b, 1), -1, x.dtype), x[:, :-1]], axis=1)
    keep = valid & (x != blank) & (x != prev)
    # left-align kept tokens: target position = exclusive cumsum of keep
    tgt = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    # scatter only kept entries (dump non-kept into a trash column)
    tgt_safe = jnp.where(keep, tgt, t)
    out_pad = jnp.full((b, t + 1), blank, x.dtype)
    out_pad = out_pad.at[rows, tgt_safe].set(
        jnp.where(keep, x, blank))
    out = out_pad[:, :t]
    out_len = jnp.sum(keep.astype(jnp.int32), axis=1)
    return {"Output": out, "OutputLen": out_len}
