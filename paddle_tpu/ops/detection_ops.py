"""Detection/vision ops.

Parity: reference paddle/fluid/operators/detection/ (prior_box_op.cc,
box_coder_op.cc, iou_similarity_op.cc, multiclass_nms_op.cc,
yolov3_loss_op.cc, yolo_box_op.cc, bipartite_match_op.cc,
target_assign_op.cc, anchor_generator_op.cc, density_prior_box_op.cc,
box_clip_op.cc, polygon_box_transform_op.cc, rpn_target_assign_op.cc,
generate_proposals_op.cc) and detection_map_op.cc.

TPU-first design: the reference's NMS/matching kernels emit
variable-length LoD outputs; XLA needs static shapes, so every
selection op here returns FIXED-size padded outputs (pad rows carry
label/index -1) with the true count available from the pad sentinel.
Suppression loops are `lax.fori_loop`s over a top-k-bounded candidate
set — O(K*M) fixed-shape work that XLA compiles into tight vector code
instead of the reference's data-dependent CPU loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op

BIG_NEG = -1e9


# ---------------------------------------------------------------------
# geometry helpers
# ---------------------------------------------------------------------
def _iou_matrix(a, b, normalized=True):
    """Pairwise IoU: a [N,4], b [M,4] (xmin,ymin,xmax,ymax)."""
    off = 0.0 if normalized else 1.0
    area_a = jnp.maximum(a[:, 2] - a[:, 0] + off, 0) * \
        jnp.maximum(a[:, 3] - a[:, 1] + off, 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0] + off, 0) * \
        jnp.maximum(b[:, 3] - b[:, 1] + off, 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _nms_indices(boxes, scores, iou_threshold, score_threshold, max_out,
                 normalized=True):
    """Fixed-size NMS: returns (idx [max_out] int32 with -1 pad)."""
    m = boxes.shape[0]
    k = min(max_out, m)
    valid = scores > score_threshold
    masked = jnp.where(valid, scores, BIG_NEG)
    iou = _iou_matrix(boxes, boxes, normalized)

    def body(i, carry):
        sel, alive, cur = carry
        best = jnp.argmax(jnp.where(alive, cur, BIG_NEG))
        ok = jnp.where(alive[best] & (cur[best] > BIG_NEG / 2),
                       best, -1)
        sel = sel.at[i].set(ok)
        suppress = (iou[best] > iou_threshold) & (ok >= 0)
        alive = alive & ~suppress & (jnp.arange(m) != best)
        return sel, alive, cur

    sel0 = jnp.full((max_out,), -1, jnp.int32)
    sel, _, _ = jax.lax.fori_loop(
        0, k, body, (sel0, valid, masked))
    return sel


# ---------------------------------------------------------------------
@register_op("iou_similarity", differentiable=False)
def iou_similarity(ctx):
    """reference detection/iou_similarity_op.cc: X [N,4] vs Y [M,4]."""
    return {"Out": _iou_matrix(ctx.input("X"), ctx.input("Y"),
                               ctx.attr("box_normalized", True))}


@register_op("box_clip", differentiable=False)
def box_clip(ctx):
    """reference detection/box_clip_op.cc: clip to im_info h/w."""
    x = ctx.input("Input")
    im = ctx.input("ImInfo")  # [B,3] (h, w, scale) or [3]

    def clip_one(boxes, info):
        h, w = info[0], info[1]
        return jnp.stack([
            jnp.clip(boxes[..., 0], 0, w - 1),
            jnp.clip(boxes[..., 1], 0, h - 1),
            jnp.clip(boxes[..., 2], 0, w - 1),
            jnp.clip(boxes[..., 3], 0, h - 1)], axis=-1)

    if im.ndim == 1:
        return {"Output": clip_one(x, im)}
    # batched: each image clips against its own (h, w)
    return {"Output": jax.vmap(clip_one)(x, im)}


@register_op("box_coder", differentiable=False)
def box_coder(ctx):
    """reference detection/box_coder_op.cc: center-size encode/decode."""
    prior = ctx.input("PriorBox")  # [M,4]
    pvar = ctx.input("PriorBoxVar")  # [M,4] | None
    target = ctx.input("TargetBox")
    code_type = ctx.attr("code_type", "encode_center_size")
    normalized = ctx.attr("box_normalized", True)
    off = 0.0 if normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    if pvar is None:
        var = jnp.asarray(ctx.attr("variance", [1.0, 1.0, 1.0, 1.0]),
                          jnp.float32)
        var = jnp.broadcast_to(var, prior.shape)
    else:
        var = pvar
    if code_type.startswith("encode"):
        # target [N,4] -> out [N, M, 4]
        tw = target[:, 2] - target[:, 0] + off
        th = target[:, 3] - target[:, 1] + off
        tcx = target[:, 0] + 0.5 * tw
        tcy = target[:, 1] + 0.5 * th
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :] / var[None, :, 0]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / var[None, :, 1]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :])) / var[None, :, 2]
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :])) / var[None, :, 3]
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
    else:
        # decode: target [N, M, 4] or [M, 4]
        t = target if target.ndim == 3 else target[None, :, :]
        ocx = pcx[None, :] + t[..., 0] * var[None, :, 0] * pw[None, :]
        ocy = pcy[None, :] + t[..., 1] * var[None, :, 1] * ph[None, :]
        ow = jnp.exp(t[..., 2] * var[None, :, 2]) * pw[None, :]
        oh = jnp.exp(t[..., 3] * var[None, :, 3]) * ph[None, :]
        out = jnp.stack([ocx - 0.5 * ow, ocy - 0.5 * oh,
                         ocx + 0.5 * ow - off, ocy + 0.5 * oh - off],
                        axis=-1)
        if target.ndim == 2:
            out = out[0]
    return {"OutputBox": out}


@register_op("prior_box", differentiable=False)
def prior_box(ctx):
    """reference detection/prior_box_op.cc: SSD priors per feature-map
    cell; outputs Boxes/Variances [H, W, P, 4]."""
    feat = ctx.input("Input")  # [B, C, H, W]
    image = ctx.input("Image")  # [B, C, IH, IW]
    min_sizes = [float(s) for s in ctx.attr("min_sizes")]
    max_sizes = [float(s) for s in ctx.attr("max_sizes", []) or []]
    ars = [float(a) for a in ctx.attr("aspect_ratios", [1.0])]
    flip = ctx.attr("flip", False)
    clip = ctx.attr("clip", False)
    variances = ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])
    step_w = ctx.attr("step_w", 0.0)
    step_h = ctx.attr("step_h", 0.0)
    offset = ctx.attr("offset", 0.5)
    min_max_ar_order = ctx.attr("min_max_aspect_ratios_order", False)

    h, w = int(feat.shape[2]), int(feat.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    sw = step_w or iw / w
    sh = step_h or ih / h
    # expand aspect ratios like the reference (1.0 first, optional flip)
    out_ars = [1.0]
    for a in ars:
        if all(abs(a - b) > 1e-6 for b in out_ars):
            out_ars.append(a)
            if flip:
                out_ars.append(1.0 / a)
    boxes = []
    for i, ms in enumerate(min_sizes):
        if min_max_ar_order:
            boxes.append((ms, ms))
            if max_sizes:
                mx = max_sizes[i]
                boxes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for a in out_ars:
                if abs(a - 1.0) < 1e-6:
                    continue
                boxes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
        else:
            for a in out_ars:
                boxes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
            if max_sizes:
                mx = max_sizes[i]
                boxes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    wh = jnp.asarray(boxes, jnp.float32)  # [P, 2]
    p = wh.shape[0]
    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    bw = wh[None, None, :, 0] * 0.5
    bh = wh[None, None, :, 1] * 0.5
    out = jnp.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                     (cxg + bw) / iw, (cyg + bh) / ih], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, p, 4))
    return {"Boxes": out, "Variances": var}


@register_op("density_prior_box", differentiable=False)
def density_prior_box(ctx):
    """reference detection/density_prior_box_op.cc."""
    feat = ctx.input("Input")
    image = ctx.input("Image")
    densities = [int(d) for d in ctx.attr("densities", [])]
    fixed_sizes = [float(s) for s in ctx.attr("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in ctx.attr("fixed_ratios", [1.0])]
    variances = ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])
    clip = ctx.attr("clip", False)
    offset = ctx.attr("offset", 0.5)
    step_w = ctx.attr("step_w", 0.0)
    step_h = ctx.attr("step_h", 0.0)
    h, w = int(feat.shape[2]), int(feat.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    sw = step_w or iw / w
    sh = step_h or ih / h
    # per cell: for each (size, density): density^2 shifted centers,
    # each with each fixed_ratio
    entries = []  # (dx, dy, bw, bh) offsets in pixels
    for size, dens in zip(fixed_sizes, densities):
        shift = size / dens
        for r in fixed_ratios:
            bw = size * np.sqrt(r)
            bh = size / np.sqrt(r)
            for di in range(dens):
                for dj in range(dens):
                    dx = -size / 2.0 + shift / 2.0 + dj * shift
                    dy = -size / 2.0 + shift / 2.0 + di * shift
                    entries.append((dx, dy, bw, bh))
    ent = jnp.asarray(entries, jnp.float32)  # [P,4]
    p = ent.shape[0]
    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    ccx = cxg[..., None] + ent[None, None, :, 0]
    ccy = cyg[..., None] + ent[None, None, :, 1]
    bw = ent[None, None, :, 2] * 0.5
    bh = ent[None, None, :, 3] * 0.5
    out = jnp.stack([(ccx - bw) / iw, (ccy - bh) / ih,
                     (ccx + bw) / iw, (ccy + bh) / ih], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, p, 4))
    return {"Boxes": out, "Variances": var}


@register_op("anchor_generator", differentiable=False)
def anchor_generator(ctx):
    """reference detection/anchor_generator_op.cc (RPN anchors,
    absolute pixel coords)."""
    feat = ctx.input("Input")
    sizes = [float(s) for s in ctx.attr("anchor_sizes")]
    ars = [float(a) for a in ctx.attr("aspect_ratios")]
    variances = ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])
    stride = [float(s) for s in ctx.attr("stride")]
    offset = ctx.attr("offset", 0.5)
    h, w = int(feat.shape[2]), int(feat.shape[3])
    # anchor_width = size*sqrt(1/ar), anchor_height = size*sqrt(ar)
    # with ar = h/w (reference anchor_generator_op.h)
    whs = [(s / np.sqrt(a), s * np.sqrt(a)) for a in ars for s in sizes]
    wh = jnp.asarray(whs, jnp.float32)
    p = wh.shape[0]
    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    bw = wh[None, None, :, 0] * 0.5
    bh = wh[None, None, :, 1] * 0.5
    anchors = jnp.stack([cxg[..., None] - bw, cyg[..., None] - bh,
                         cxg[..., None] + bw, cyg[..., None] + bh],
                        axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, p, 4))
    return {"Anchors": anchors, "Variances": var}


@register_op("bipartite_match", differentiable=False)
def bipartite_match(ctx):
    """reference detection/bipartite_match_op.cc: greedy argmax
    matching on DistMat [B, N, M] (N gt rows, M priors). Outputs
    ColToRowMatchIndices [B, M] (-1 unmatched) + matched distances.
    match_type='per_prediction' additionally matches cols whose best
    row similarity exceeds dist_threshold."""
    dist = ctx.input("DistMat")
    batched = dist.ndim == 3
    if not batched:
        dist = dist[None]
    match_type = ctx.attr("match_type", "bipartite")
    thresh = ctx.attr("dist_threshold", 0.5)
    b, n, m = dist.shape

    def one(d):
        def body(i, carry):
            match, matchd, dd = carry
            flat = jnp.argmax(dd)
            r, c = flat // m, flat % m
            ok = dd[r, c] > 0
            match = jnp.where(ok, match.at[c].set(r.astype(jnp.int32)),
                              match)
            matchd = jnp.where(ok, matchd.at[c].set(d[r, c]), matchd)
            dd = jnp.where(ok, dd.at[r, :].set(BIG_NEG)
                           .at[:, c].set(BIG_NEG), dd)
            return match, matchd, dd

        match0 = jnp.full((m,), -1, jnp.int32)
        matchd0 = jnp.zeros((m,), dist.dtype)
        match, matchd, _ = jax.lax.fori_loop(
            0, min(n, m), body, (match0, matchd0, d))
        if match_type == "per_prediction":
            best_row = jnp.argmax(d, axis=0).astype(jnp.int32)
            best_sim = jnp.max(d, axis=0)
            extra = (match < 0) & (best_sim > thresh)
            match = jnp.where(extra, best_row, match)
            matchd = jnp.where(extra, best_sim, matchd)
        return match, matchd

    match, matchd = jax.vmap(one)(dist)
    if not batched:
        match, matchd = match[0], matchd[0]
    return {"ColToRowMatchIndices": match, "ColToRowMatchDist": matchd}


@register_op("target_assign", differentiable=False)
def target_assign(ctx):
    """reference detection/target_assign_op.cc: out[i,j] =
    X[match[i,j]] where matched else mismatch_value."""
    x = ctx.input("X")  # [N, K] or [B, N, K]
    match = ctx.input("MatchIndices")  # [B, M]
    neg = ctx.input("NegIndices")  # optional [B, Nn], pad rows -1
    mismatch = ctx.attr("mismatch_value", 0)
    if x.ndim == 2:
        x = jnp.broadcast_to(x[None], (match.shape[0],) + x.shape)
    if neg is not None and neg.ndim == 1:
        neg = jnp.broadcast_to(neg[None], (match.shape[0],) + neg.shape)

    def one(xb, mb, nb):
        safe = jnp.maximum(mb, 0)
        out = xb[safe]
        w = (mb >= 0)
        out = jnp.where(w[:, None], out,
                        jnp.asarray(mismatch, x.dtype))
        w = w.astype(x.dtype)
        if nb is not None:
            # reference target_assign_op.cc NegIndices branch: mined
            # negatives keep the mismatch value but get weight 1 so the
            # background class trains on them
            m_len = w.shape[0]
            # pad entries (nb < 0) route to index m_len and are dropped
            neg_mask = jnp.zeros((m_len,), bool).at[
                jnp.where(nb >= 0, nb, m_len)].set(True, mode="drop")
            w = jnp.where(neg_mask, jnp.asarray(1.0, x.dtype), w)
        return out, w

    if neg is None:
        out, w = jax.vmap(lambda xb, mb: one(xb, mb, None))(x, match)
    else:
        out, w = jax.vmap(one)(x, match, neg)
    return {"Out": out, "OutWeight": w[..., None]}


@register_op("multiclass_nms", differentiable=False)
def multiclass_nms(ctx):
    """reference detection/multiclass_nms_op.cc. BBoxes [B, M, 4],
    Scores [B, C, M] -> Out [B, keep_top_k, 6] rows
    (label, score, x1, y1, x2, y2); pad rows have label -1.

    (The reference emits a variable-row LoDTensor; fixed-size padding is
    the XLA-native encoding of the same information.)"""
    bboxes = ctx.input("BBoxes")
    scores = ctx.input("Scores")
    score_thresh = ctx.attr("score_threshold", 0.0)
    nms_top_k = ctx.attr("nms_top_k", 100)
    keep_top_k = ctx.attr("keep_top_k", 100)
    nms_thresh = ctx.attr("nms_threshold", 0.3)
    normalized = ctx.attr("normalized", True)
    background = ctx.attr("background_label", 0)
    b, m, _ = bboxes.shape
    c = scores.shape[1]
    per_class = min(nms_top_k, m)

    def one_image(boxes, sc):
        # per-class NMS -> [C, per_class] indices
        def per_cls(cls_scores):
            return _nms_indices(boxes, cls_scores, nms_thresh,
                                score_thresh, per_class, normalized)

        idx = jax.vmap(per_cls)(sc)  # [C, per_class]
        cls_ids = jnp.broadcast_to(
            jnp.arange(c, dtype=jnp.int32)[:, None], idx.shape)
        valid = (idx >= 0) & (cls_ids != background)
        flat_idx = idx.reshape(-1)
        flat_cls = cls_ids.reshape(-1)
        flat_valid = valid.reshape(-1)
        flat_scores = jnp.where(
            flat_valid,
            sc[flat_cls, jnp.maximum(flat_idx, 0)], BIG_NEG)
        k = min(keep_top_k, flat_scores.shape[0])
        top_sc, top_i = jax.lax.top_k(flat_scores, k)
        sel_box = boxes[jnp.maximum(flat_idx[top_i], 0)]
        sel_cls = flat_cls[top_i].astype(bboxes.dtype)
        ok = top_sc > BIG_NEG / 2
        row = jnp.concatenate(
            [jnp.where(ok, sel_cls, -1.0)[:, None],
             jnp.where(ok, top_sc, 0.0)[:, None],
             jnp.where(ok[:, None], sel_box, 0.0)], axis=1)
        if k < keep_top_k:
            row = jnp.concatenate(
                [row, jnp.tile(jnp.asarray([[-1., 0, 0, 0, 0, 0]],
                                           row.dtype),
                               (keep_top_k - k, 1))], axis=0)
        return row

    return {"Out": jax.vmap(one_image)(bboxes, scores)}


@register_op("yolo_box", differentiable=False)
def yolo_box(ctx):
    """reference detection/yolo_box_op.cc: decode YOLOv3 head."""
    x = ctx.input("X")  # [B, A*(5+C), H, W]
    img_size = ctx.input("ImgSize")  # [B, 2] (h, w)
    anchors = [int(a) for a in ctx.attr("anchors")]
    class_num = ctx.attr("class_num")
    conf_thresh = ctx.attr("conf_thresh", 0.01)
    downsample = ctx.attr("downsample_ratio", 32)
    b, _, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    x = x.reshape(b, na, 5 + class_num, h, w)
    gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(x[:, :, 0]) + gx) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + gy) / h
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / (
        downsample * w)
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / (
        downsample * h)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    boxes = jnp.stack([(bx - bw / 2) * imw, (by - bh / 2) * imh,
                       (bx + bw / 2) * imw, (by + bh / 2) * imh],
                      axis=2)  # [B, A, 4, H, W]
    mask = (conf > conf_thresh).astype(x.dtype)
    boxes = boxes * mask[:, :, None]
    probs = probs * mask[:, :, None]
    boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(b, na * h * w, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(
        b, na * h * w, class_num)
    return {"Boxes": boxes, "Scores": scores}


def _yolov3_loss_grad_maker(op, no_grad_set=frozenset()):
    from ..core.program import Operator, grad_var_name

    inputs = {k: list(v) for k, v in op.inputs.items()}
    inputs["Loss@GRAD"] = [grad_var_name(op.output("Loss")[0])]
    return [Operator(op.block, "yolov3_loss_grad", inputs,
                     {"X@GRAD": [grad_var_name(op.input("X")[0])]},
                     dict(op.attrs))]


def _yolov3_loss_impl(x, gt_box, gt_label, anchors, anchor_mask,
                      class_num, ignore_thresh, downsample):
    """YOLOv3 loss (reference yolov3_loss_op.h): coord MSE/BCE +
    objectness BCE with ignore region + class BCE."""
    b, _, h, w = x.shape
    na = len(anchor_mask)
    all_an = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    an = all_an[jnp.asarray(anchor_mask, jnp.int32)]
    xr = x.reshape(b, na, 5 + class_num, h, w)
    px, py = xr[:, :, 0], xr[:, :, 1]
    pw, ph = xr[:, :, 2], xr[:, :, 3]
    pobj = xr[:, :, 4]
    pcls = xr[:, :, 5:]

    in_w = downsample * w
    in_h = downsample * h
    g = gt_box.shape[1]
    # gt in [0,1] center-size (reference format)
    gx = gt_box[..., 0] * w
    gy = gt_box[..., 1] * h
    gw = gt_box[..., 2] * in_w
    gh = gt_box[..., 3] * in_h
    gi = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
    gj = jnp.clip(gy.astype(jnp.int32), 0, h - 1)
    valid_gt = (gt_box[..., 2] > 0) & (gt_box[..., 3] > 0)
    # best anchor per gt by wh IoU against ALL anchors (reference picks
    # over the full anchor set, then checks membership in anchor_mask)
    inter = jnp.minimum(gw[..., None], all_an[None, None, :, 0]) * \
        jnp.minimum(gh[..., None], all_an[None, None, :, 1])
    union = gw[..., None] * gh[..., None] + \
        all_an[None, None, :, 0] * all_an[None, None, :, 1] - inter
    an_iou = inter / jnp.maximum(union, 1e-10)
    best_an = jnp.argmax(an_iou, axis=-1)  # [B, G] in all-anchor ids
    mask_arr = jnp.asarray(anchor_mask, jnp.int32)
    in_mask = (best_an[..., None] == mask_arr[None, None, :])
    local_an = jnp.argmax(in_mask, axis=-1)  # [B, G] position in mask
    use_gt = valid_gt & in_mask.any(axis=-1)

    sig = jax.nn.sigmoid

    def bce(logit, label):
        return jnp.maximum(logit, 0) - logit * label + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    # scatter per-gt targets onto the grid
    obj_target = jnp.zeros((b, na, h, w))
    loss_acc = jnp.zeros((b,))
    bidx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, g))
    scale = 2.0 - gt_box[..., 2] * gt_box[..., 3]
    tx = gx - gi
    ty = gy - gj
    tw = jnp.log(jnp.maximum(
        gw / jnp.maximum(an[local_an][..., 0], 1e-10), 1e-10))
    th = jnp.log(jnp.maximum(
        gh / jnp.maximum(an[local_an][..., 1], 1e-10), 1e-10))
    sel = (bidx, local_an, gj, gi)
    wgt = jnp.where(use_gt, scale, 0.0)
    loss_xy = bce(px[sel], tx) * wgt + bce(py[sel], ty) * wgt
    loss_wh = (jnp.square(pw[sel] - tw) + jnp.square(ph[sel] - th)) * \
        wgt * 0.5
    cls_onehot = jax.nn.one_hot(gt_label, class_num)
    loss_cls = jnp.sum(bce(pcls.transpose(0, 1, 3, 4, 2)[sel],
                           cls_onehot), -1) * jnp.where(use_gt, 1.0, 0.0)
    obj_target = obj_target.at[sel].max(
        jnp.where(use_gt, 1.0, 0.0))
    # objectness: positives BCE(1); negatives BCE(0) unless best-gt IoU
    # above ignore_thresh
    pred_boxes = jnp.stack([
        (sig(px) + jnp.arange(w)[None, None, None, :]) / w,
        (sig(py) + jnp.arange(h)[None, None, :, None]) / h,
        jnp.exp(pw) * an[None, :, 0, None, None] / in_w,
        jnp.exp(ph) * an[None, :, 1, None, None] / in_h], axis=-1)
    pb = pred_boxes.reshape(b, -1, 4)
    pb_xyxy = jnp.concatenate([pb[..., :2] - pb[..., 2:] / 2,
                               pb[..., :2] + pb[..., 2:] / 2], -1)
    gt_xyxy = jnp.concatenate([gt_box[..., :2] - gt_box[..., 2:] / 2,
                               gt_box[..., :2] + gt_box[..., 2:] / 2],
                              -1)

    def best_iou(pbi, gbi, vgi):
        mat = _iou_matrix(pbi, gbi)
        return jnp.max(jnp.where(vgi[None, :], mat, 0.0), axis=1)

    biou = jax.vmap(best_iou)(pb_xyxy, gt_xyxy, valid_gt)
    ignore = (biou > ignore_thresh).reshape(b, na, h, w)
    noobj_w = jnp.where((obj_target < 0.5) & ~ignore, 1.0, 0.0)
    loss_obj = bce(pobj, jnp.ones_like(pobj)) * obj_target + \
        bce(pobj, jnp.zeros_like(pobj)) * noobj_w
    total = (jnp.sum(loss_xy, 1) + jnp.sum(loss_wh, 1)
             + jnp.sum(loss_cls, 1)
             + jnp.sum(loss_obj, (1, 2, 3)))
    return total + loss_acc


@register_op("yolov3_loss", grad_maker=_yolov3_loss_grad_maker,
             stop_gradient_slots=("GTBox", "GTLabel"))
def yolov3_loss(ctx):
    loss = _yolov3_loss_impl(
        ctx.input("X"), ctx.input("GTBox"), ctx.input("GTLabel"),
        [int(a) for a in ctx.attr("anchors")],
        [int(a) for a in ctx.attr("anchor_mask")],
        ctx.attr("class_num"), ctx.attr("ignore_thresh", 0.7),
        ctx.attr("downsample_ratio", 32))
    return {"Loss": loss}


@register_op("yolov3_loss_grad", differentiable=False)
def yolov3_loss_grad(ctx):
    dl = ctx.input("Loss@GRAD")
    args = (ctx.input("GTBox"), ctx.input("GTLabel"),
            [int(a) for a in ctx.attr("anchors")],
            [int(a) for a in ctx.attr("anchor_mask")],
            ctx.attr("class_num"), ctx.attr("ignore_thresh", 0.7),
            ctx.attr("downsample_ratio", 32))
    grad = jax.grad(
        lambda xx: jnp.sum(_yolov3_loss_impl(xx, *args) * dl))(
            ctx.input("X"))
    return {"X@GRAD": grad}


@register_op("polygon_box_transform", differentiable=False)
def polygon_box_transform(ctx):
    """reference detection/polygon_box_transform_op.cc: input [B, 2K,
    H, W] offsets -> absolute coords: out = 4*(col,row) - in for
    activated cells (reference semantics: out(x)= id*4 - in)."""
    x = ctx.input("Input")
    b, c, h, w = x.shape
    col = jnp.broadcast_to(jnp.arange(w, dtype=x.dtype)[None, :],
                           (h, w))
    row = jnp.broadcast_to(jnp.arange(h, dtype=x.dtype)[:, None],
                           (h, w))
    idx = jnp.stack([col, row] * (c // 2), 0)  # [C, H, W]
    return {"Output": idx[None] * 4.0 - x}


def compute_map_np(det_batches, lab_batches, overlap=0.5,
                   ap_type="integral", background_label=0,
                   evaluate_difficult=True, has_difficult=False):
    """Pooled mAP over a list of per-image (det [D,6], gt [G,5|6])
    numpy arrays (reference detection_map_op.cc semantics): scores are
    ranked globally per class, gt rows with label==background_label
    (or label<0 padding) are excluded, and with evaluate_difficult
    False a detection matched to a difficult gt is IGNORED (neither TP
    nor FP) while difficult gt do not count toward npos. Shared by the
    detection_map op (one batch) and metrics.DetectionMAP (dataset
    accumulation)."""
    box_col = 2 if has_difficult else 1
    classes = set()
    for lab in lab_batches:
        for row in np.asarray(lab):
            l = int(row[0])
            if l >= 0 and l != background_label:
                classes.add(l)
    aps = []
    for cls in classes:
        scores, marks = [], []  # mark: 1 tp, 0 fp (ignored = skipped)
        npos = 0
        for det_np, lab_np in zip(det_batches, lab_batches):
            det_np = np.asarray(det_np)
            lab_np = np.asarray(lab_np)
            sel = lab_np[lab_np[:, 0] == cls]
            gt = sel[:, box_col:box_col + 4]
            difficult = (sel[:, 1].astype(bool) if has_difficult
                         else np.zeros(len(sel), bool))
            npos += int((~difficult).sum()) if not evaluate_difficult \
                else len(gt)
            dt = det_np[det_np[:, 0] == cls]
            dt = dt[np.argsort(-dt[:, 1])]
            used = np.zeros(len(gt), bool)
            for row in dt:
                box = row[2:6]
                best, gi_best = 0.0, -1
                for gi, g in enumerate(gt):
                    iw = max(min(box[2], g[2]) - max(box[0], g[0]), 0)
                    ih = max(min(box[3], g[3]) - max(box[1], g[1]), 0)
                    inter = iw * ih
                    ua = ((box[2] - box[0]) * (box[3] - box[1])
                          + (g[2] - g[0]) * (g[3] - g[1]) - inter)
                    iou = inter / ua if ua > 0 else 0
                    if iou > best:
                        best, gi_best = iou, gi
                matched = best >= overlap and gi_best >= 0
                if matched and not evaluate_difficult \
                        and difficult[gi_best]:
                    continue  # ignore: neither tp nor fp
                tp = matched and not used[gi_best]
                if tp:
                    used[gi_best] = True
                scores.append(row[1])
                marks.append(1.0 if tp else 0.0)
        if npos == 0:
            continue
        order = np.argsort(-np.asarray(scores)) if scores else []
        tps_s = np.asarray(marks)[order] if marks else np.zeros(0)
        ctp = np.cumsum(tps_s)
        prec = ctp / (np.arange(len(ctp)) + 1) if len(ctp) else \
            np.zeros(0)
        rec = ctp / npos if len(ctp) else np.zeros(0)
        if ap_type == "11point":
            ap = float(np.mean([
                max([p for p, r in zip(prec, rec) if r >= t],
                    default=0.0) for t in np.linspace(0, 1, 11)]))
        else:
            ap = 0.0
            prev_r = 0.0
            for p, r in zip(prec, rec):
                ap += p * (r - prev_r)
                prev_r = r
        aps.append(ap)
    return float(np.mean(aps)) if aps else 0.0


@register_op("detection_map", differentiable=False,
             host_effect=True)
def detection_map(ctx):
    """reference detection_map_op.cc: mAP over padded NMS detections
    (label -1 rows = padding) vs padded gt (label -1 = padding). Host
    computation via io_callback (compute_map_np) — metrics are not a
    device hot path. Attrs: overlap_threshold, ap_type,
    background_label, evaluate_difficult, has_difficult (gt layout
    [label, difficult, x1..] vs [label, x1..])."""
    det = ctx.input("DetectRes")  # [B, D, 6]
    label = ctx.input("Label")  # [B, G, 5|6]
    overlap = ctx.attr("overlap_threshold", 0.5)
    ap_type = ctx.attr("ap_type", "integral")
    background = ctx.attr("background_label", 0)
    eval_diff = ctx.attr("evaluate_difficult", True)
    has_diff = ctx.attr("has_difficult", False)

    def _map(det_np, lab_np):
        det_np = np.asarray(det_np)
        lab_np = np.asarray(lab_np)
        return np.asarray(compute_map_np(
            list(det_np), list(lab_np), overlap=overlap,
            ap_type=ap_type, background_label=background,
            evaluate_difficult=eval_diff, has_difficult=has_diff),
            np.float32)

    from jax.experimental import io_callback

    out = io_callback(_map, jax.ShapeDtypeStruct((), jnp.float32),
                      det, label, ordered=True)
    return {"MAP": out, "AccumPosCount": jnp.zeros((1,), jnp.int32),
            "AccumTruePos": jnp.zeros((1, 2)),
            "AccumFalsePos": jnp.zeros((1, 2))}


@register_op("ssd_loss", stop_gradient_slots=("GTBox", "GTLabel",
                                              "PriorBox", "PriorBoxVar"))
def ssd_loss(ctx):
    """Fused SSD multibox loss (reference layers/detection.py ssd_loss
    composes ~10 ops: iou_similarity -> bipartite_match ->
    target_assign -> mine_hard_examples -> smooth_l1 + softmax CE; here
    it is ONE fused XLA kernel — matching, hard negative mining and
    both losses in a single compiled region, grad via auto-vjp).

    Inputs: Location [B, M, 4], Confidence [B, M, C],
    GTBox [B, G, 4] (xyxy, padded rows all-zero), GTLabel [B, G, 1],
    PriorBox [M, 4], PriorBoxVar [M, 4].
    Output: Loss [B, 1]."""
    loc = ctx.input("Location")
    conf = ctx.input("Confidence")
    gt_box = ctx.input("GTBox")
    gt_label = ctx.input("GTLabel")
    prior = ctx.input("PriorBox")
    pvar = ctx.input("PriorBoxVar")
    if pvar is None:
        pvar = jnp.broadcast_to(
            jnp.asarray([0.1, 0.1, 0.2, 0.2], loc.dtype), prior.shape)
    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]
    neg_pos_ratio = ctx.attr("neg_pos_ratio", 3.0)
    overlap_threshold = ctx.attr("overlap_threshold", 0.5)
    neg_overlap = ctx.attr("neg_overlap", 0.5)
    conf_loss_weight = ctx.attr("conf_loss_weight", 1.0)
    loc_loss_weight = ctx.attr("loc_loss_weight", 1.0)
    background_label = ctx.attr("background_label", 0)
    match_type = ctx.attr("match_type", "per_prediction")
    mining_type = ctx.attr("mining_type", "max_negative")
    normalize = ctx.attr("normalize", True)
    sample_size = ctx.attr("sample_size", 0)
    b, m, _ = loc.shape

    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph

    def one(loc_i, conf_i, gtb, gtl):
        valid_gt = (gtb[:, 2] - gtb[:, 0]) * (gtb[:, 3] - gtb[:, 1]) > 0
        sim = _iou_matrix(gtb, prior)  # [G, M]
        sim = jnp.where(valid_gt[:, None], sim, 0.0)
        g = sim.shape[0]
        # bipartite base match: each gt greedily claims its best prior
        # (reference bipartite_match_op); per_prediction additionally
        # matches priors whose best-gt IoU exceeds overlap_threshold
        def bip_body(_, carry):
            matched_b, claim, sm = carry
            flat = jnp.argmax(sm)
            r, c = flat // m, flat % m
            ok = sm[r, c] > 0
            matched_b = jnp.where(ok, matched_b.at[c].set(True),
                                  matched_b)
            claim = jnp.where(ok, claim.at[c].set(r), claim)
            sm = jnp.where(ok, sm.at[r, :].set(BIG_NEG)
                           .at[:, c].set(BIG_NEG), sm)
            return matched_b, claim, sm

        bip_matched, bip_claim, _ = jax.lax.fori_loop(
            0, min(g, m), bip_body,
            (jnp.zeros((m,), bool), jnp.zeros((m,), jnp.int32), sim))
        best_gt = jnp.argmax(sim, axis=0)  # per prior
        best_sim = jnp.max(sim, axis=0)
        if match_type == "per_prediction":
            matched = bip_matched | (best_sim > overlap_threshold)
        else:
            matched = bip_matched
        # a prior claimed in the greedy bipartite pass takes the gt row
        # that claimed it -- two gts contesting one prior can leave
        # argmax-IoU pointing at the loser (reference bipartite_match ->
        # target_assign gathers by the assigned row); per_prediction
        # extras fall back to argmax
        tgt_row = jnp.where(bip_matched, bip_claim, best_gt)
        tgt_box = gtb[tgt_row]
        tgt_label = jnp.where(matched, gtl[tgt_row].astype(jnp.int32),
                              background_label)
        # encode matched boxes against priors (center-size + variance)
        tw = tgt_box[:, 2] - tgt_box[:, 0]
        th = tgt_box[:, 3] - tgt_box[:, 1]
        tcx = tgt_box[:, 0] + 0.5 * tw
        tcy = tgt_box[:, 1] + 0.5 * th
        enc = jnp.stack([
            (tcx - pcx) / jnp.maximum(pw, 1e-10) / pvar[:, 0],
            (tcy - pcy) / jnp.maximum(ph, 1e-10) / pvar[:, 1],
            jnp.log(jnp.maximum(tw / jnp.maximum(pw, 1e-10), 1e-10))
            / pvar[:, 2],
            jnp.log(jnp.maximum(th / jnp.maximum(ph, 1e-10), 1e-10))
            / pvar[:, 3]], axis=-1)
        # smooth-l1 loc loss on positives
        d = loc_i - enc
        ad = jnp.abs(d)
        sl1 = jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5).sum(-1)
        n_pos = jnp.maximum(matched.sum(), 1)
        loc_loss = jnp.sum(jnp.where(matched, sl1, 0.0))
        # softmax CE conf loss, hard-negative mined at neg_pos_ratio
        logp = jax.nn.log_softmax(conf_i, axis=-1)
        ce = -jnp.take_along_axis(logp, tgt_label[:, None],
                                  axis=-1)[:, 0]
        # negatives: unmatched priors whose best overlap stays below
        # neg_overlap (reference mine_hard_examples semantics)
        neg_cand = (~matched) & (best_sim < neg_overlap)
        neg_ce = jnp.where(neg_cand, ce, BIG_NEG)
        n_neg = jnp.minimum(
            (neg_pos_ratio * n_pos).astype(jnp.int32), m)
        if mining_type == "hard_example" and sample_size:
            n_neg = jnp.minimum(n_neg, sample_size)
        sorted_neg = jnp.sort(neg_ce)[::-1]
        thresh = sorted_neg[jnp.clip(n_neg - 1, 0, m - 1)]
        neg_sel = neg_cand & (ce >= thresh) & (n_neg > 0)
        conf_loss = jnp.sum(jnp.where(matched | neg_sel, ce, 0.0))
        total = (conf_loss_weight * conf_loss
                 + loc_loss_weight * loc_loss)
        return total / n_pos if normalize else total

    return {"Loss": jax.vmap(one)(loc, conf, gt_box, gt_label)[:, None]}


@register_op("rpn_target_assign", differentiable=False, needs_rng=True)
def rpn_target_assign(ctx):
    """reference detection/rpn_target_assign_op.cc: label anchors as
    fg (IoU > positive_overlap or best-per-gt), bg (IoU <
    negative_overlap), sample to rpn_batch_size_per_im with fg
    fraction. Fixed-shape outputs: per-anchor labels [-1 ignore, 0 bg,
    1 fg] and encoded bbox targets (padded selection stays static)."""
    anchor = ctx.input("Anchor")  # [M, 4]
    gt_boxes = ctx.input("GtBoxes")  # [B, G, 4]
    pos_overlap = ctx.attr("rpn_positive_overlap", 0.7)
    neg_overlap = ctx.attr("rpn_negative_overlap", 0.3)
    batch_per_im = ctx.attr("rpn_batch_size_per_im", 256)
    fg_frac = ctx.attr("rpn_fg_fraction", 0.5)
    use_random = ctx.attr("use_random", True)
    key = ctx.rng()
    m = anchor.shape[0]

    def one(gtb, k):
        valid = (gtb[:, 2] - gtb[:, 0]) * (gtb[:, 3] - gtb[:, 1]) > 0
        iou = _iou_matrix(anchor, gtb)
        iou = jnp.where(valid[None, :], iou, 0.0)
        best_per_anchor = jnp.max(iou, axis=1)
        gt_per_anchor = jnp.argmax(iou, axis=1)
        # anchors that are argmax for some gt are fg too
        best_per_gt = jnp.max(iou, axis=0)
        is_best = jnp.any(
            (iou == best_per_gt[None, :]) & valid[None, :] &
            (best_per_gt[None, :] > 0), axis=1)
        fg = (best_per_anchor >= pos_overlap) | is_best
        bg = (best_per_anchor < neg_overlap) & ~fg
        # subsample: random scores (or deterministic IoU ranking when
        # use_random=False, for reproducible tests), keep top n_fg/n_bg
        n_fg = int(batch_per_im * fg_frac)
        r1, r2 = jax.random.split(k)
        if use_random:
            fg_scores = jax.random.uniform(r1, (m,))
            bg_scores = jax.random.uniform(r2, (m,))
        else:
            fg_scores = best_per_anchor
            bg_scores = -best_per_anchor
        fg_rank = jnp.where(fg, fg_scores, BIG_NEG)
        fg_keep = fg & (fg_rank >=
                        jnp.sort(fg_rank)[::-1][
                            jnp.minimum(n_fg, m) - 1])
        n_bg = batch_per_im - jnp.minimum(fg_keep.sum(), n_fg)
        bg_rank = jnp.where(bg, bg_scores, BIG_NEG)
        bg_thresh = jnp.sort(bg_rank)[::-1][
            jnp.clip(n_bg - 1, 0, m - 1)]
        bg_keep = bg & (bg_rank >= bg_thresh) & (n_bg > 0)
        label = jnp.where(fg_keep, 1, jnp.where(bg_keep, 0, -1))
        tgt = gtb[gt_per_anchor]
        # encode center-size targets
        pw = anchor[:, 2] - anchor[:, 0]
        ph = anchor[:, 3] - anchor[:, 1]
        pcx = anchor[:, 0] + 0.5 * pw
        pcy = anchor[:, 1] + 0.5 * ph
        tw = tgt[:, 2] - tgt[:, 0]
        th = tgt[:, 3] - tgt[:, 1]
        enc = jnp.stack([
            (tgt[:, 0] + 0.5 * tw - pcx) / jnp.maximum(pw, 1e-10),
            (tgt[:, 1] + 0.5 * th - pcy) / jnp.maximum(ph, 1e-10),
            jnp.log(jnp.maximum(tw / jnp.maximum(pw, 1e-10), 1e-10)),
            jnp.log(jnp.maximum(th / jnp.maximum(ph, 1e-10), 1e-10))],
            -1)
        return label.astype(jnp.int32), enc

    keys = jax.random.split(key, gt_boxes.shape[0])
    labels, targets = jax.vmap(one)(gt_boxes, keys)
    return {"LocationIndex": labels, "ScoreIndex": labels,
            "TargetLabel": labels, "TargetBBox": targets,
            "BBoxInsideWeight": (labels == 1).astype(anchor.dtype)
            [..., None]}


@register_op("generate_proposals", differentiable=False)
def generate_proposals(ctx):
    """reference detection/generate_proposals_op.cc: decode RPN deltas
    at anchors, clip to image, NMS -> fixed post_nms_topN padded
    proposals per image."""
    scores = ctx.input("Scores")  # [B, A, H, W]
    deltas = ctx.input("BboxDeltas")  # [B, A*4, H, W]
    im_info = ctx.input("ImInfo")  # [B, 3]
    anchors = ctx.input("Anchors")  # [H, W, A, 4]
    pre_n = ctx.attr("pre_nms_topN", 6000)
    post_n = ctx.attr("post_nms_topN", 1000)
    nms_thresh = ctx.attr("nms_thresh", 0.7)
    min_size = ctx.attr("min_size", 0.1)
    b = scores.shape[0]
    a = scores.shape[1]
    h, w = scores.shape[2], scores.shape[3]
    anc = anchors.reshape(-1, 4)  # [H*W*A, 4] (H, W, A order)

    def one(sc, dl, im):
        sc = sc.transpose(1, 2, 0).reshape(-1)  # H, W, A
        dl = dl.reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        pw = anc[:, 2] - anc[:, 0] + 1
        ph = anc[:, 3] - anc[:, 1] + 1
        pcx = anc[:, 0] + 0.5 * pw
        pcy = anc[:, 1] + 0.5 * ph
        cx = pcx + dl[:, 0] * pw
        cy = pcy + dl[:, 1] * ph
        bw = jnp.exp(jnp.minimum(dl[:, 2], 10.0)) * pw
        bh = jnp.exp(jnp.minimum(dl[:, 3], 10.0)) * ph
        boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2 - 1, cy + bh / 2 - 1], -1)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, im[1] - 1),
            jnp.clip(boxes[:, 1], 0, im[0] - 1),
            jnp.clip(boxes[:, 2], 0, im[1] - 1),
            jnp.clip(boxes[:, 3], 0, im[0] - 1)], -1)
        ok = ((boxes[:, 2] - boxes[:, 0] >= min_size) &
              (boxes[:, 3] - boxes[:, 1] >= min_size))
        sc = jnp.where(ok, sc, BIG_NEG)
        k = min(pre_n, sc.shape[0])
        top_sc, top_i = jax.lax.top_k(sc, k)
        idx = _nms_indices(boxes[top_i], top_sc, nms_thresh,
                           BIG_NEG / 2, post_n, normalized=False)
        sel = jnp.maximum(idx, 0)
        rois = jnp.where((idx >= 0)[:, None], boxes[top_i][sel], 0.0)
        roi_scores = jnp.where(idx >= 0, top_sc[sel], 0.0)
        return rois, roi_scores

    rois, rscores = jax.vmap(one)(scores, deltas, im_info)
    return {"RpnRois": rois, "RpnRoiProbs": rscores[..., None]}


@register_op("generate_proposal_labels", differentiable=False,
             needs_rng=True)
def generate_proposal_labels(ctx):
    """reference detection/generate_proposal_labels_op.cc: match rois
    to gt by IoU, label fg (iou >= fg_thresh, gt class) / bg
    (bg_thresh_lo <= iou < bg_thresh_hi, label 0) / ignore (-1),
    subsample to batch_size_per_im at fg_fraction, and emit encoded
    bbox regression targets. Fixed shapes: labels/targets per roi,
    unsampled rois labeled -1."""
    rois = ctx.input("RpnRois")  # [B, N, 4]
    gt_classes = ctx.input("GtClasses")  # [B, G]
    gt_boxes = ctx.input("GtBoxes")  # [B, G, 4]
    fg_thresh = ctx.attr("fg_thresh", 0.5)
    bg_hi = ctx.attr("bg_thresh_hi", 0.5)
    bg_lo = ctx.attr("bg_thresh_lo", 0.0)
    batch_per_im = ctx.attr("batch_size_per_im", 256)
    fg_frac = ctx.attr("fg_fraction", 0.25)
    weights = jnp.asarray(
        ctx.attr("bbox_reg_weights", [0.1, 0.1, 0.2, 0.2]), jnp.float32)
    use_random = ctx.attr("use_random", True)
    key = ctx.rng()
    n = rois.shape[1]

    def one(r, gc, gb, k):
        valid = (gb[:, 2] - gb[:, 0]) * (gb[:, 3] - gb[:, 1]) > 0
        iou = _iou_matrix(r, gb)  # [N, G]
        iou = jnp.where(valid[None, :], iou, 0.0)
        best = jnp.max(iou, axis=1)
        gt_i = jnp.argmax(iou, axis=1)
        fg = best >= fg_thresh
        bg = (best >= bg_lo) & (best < bg_hi)
        n_fg = int(batch_per_im * fg_frac)
        if use_random:
            r1, r2 = jax.random.split(k)
            fg_rank = jax.random.uniform(r1, (n,))
            bg_rank = jax.random.uniform(r2, (n,))
        else:
            # deterministic: prefer higher IoU fg, lower IoU bg
            fg_rank = best
            bg_rank = -best
        fg_score = jnp.where(fg, fg_rank, BIG_NEG)
        fg_keep = fg & (fg_score >= jnp.sort(fg_score)[::-1][
            jnp.clip(n_fg - 1, 0, n - 1)])
        n_bg = batch_per_im - jnp.minimum(fg_keep.sum(), n_fg)
        bg_score = jnp.where(bg, bg_rank, BIG_NEG)
        bg_keep = bg & (bg_score >= jnp.sort(bg_score)[::-1][
            jnp.clip(n_bg - 1, 0, n - 1)]) & (n_bg > 0)
        label = jnp.where(fg_keep, gc[gt_i].astype(jnp.int32),
                          jnp.where(bg_keep, 0, -1))
        tgt = gb[gt_i]
        pw = jnp.maximum(r[:, 2] - r[:, 0], 1e-10)
        ph = jnp.maximum(r[:, 3] - r[:, 1], 1e-10)
        tw = jnp.maximum(tgt[:, 2] - tgt[:, 0], 1e-10)
        th = jnp.maximum(tgt[:, 3] - tgt[:, 1], 1e-10)
        enc = jnp.stack([
            ((tgt[:, 0] + tw / 2) - (r[:, 0] + pw / 2)) / pw
            / weights[0],
            ((tgt[:, 1] + th / 2) - (r[:, 1] + ph / 2)) / ph
            / weights[1],
            jnp.log(tw / pw) / weights[2],
            jnp.log(th / ph) / weights[3]], -1)
        inside = (label > 0).astype(r.dtype)[:, None] * \
            jnp.ones((1, 4), r.dtype)
        return label, jnp.where((label > 0)[:, None], enc, 0.0), inside

    keys = jax.random.split(key, rois.shape[0])
    labels, targets, inside = jax.vmap(one)(rois, gt_classes, gt_boxes,
                                            keys)
    return {"Rois": rois, "LabelsInt32": labels,
            "BboxTargets": targets, "BboxInsideWeights": inside,
            "BboxOutsideWeights": inside}


# ---------------------------------------------------------------------
# batch 3 additions (reference detection/box_decoder_and_assign_op.cc,
# distribute_fpn_proposals_op.cc, roi_perspective_transform_op.cc,
# generate_mask_labels_op.cc)
# ---------------------------------------------------------------------
@register_op("box_decoder_and_assign", differentiable=False)
def box_decoder_and_assign(ctx):
    """reference detection/box_decoder_and_assign_op.h: decode per-class
    regression deltas against PriorBox (+1-offset corner convention),
    clip dw/dh at box_clip, then assign each roi the decoded box of its
    max-score non-background class (fallback: the prior itself)."""
    prior = ctx.input("PriorBox")          # N,4
    pvar = ctx.input("PriorBoxVar")        # [4] or per-prior [N,4]
    tgt = ctx.input("TargetBox")           # N,C*4
    score = ctx.input("BoxScore")          # N,C
    clip = ctx.attr("box_clip", 2.302585)  # ln(10)
    n = prior.shape[0]
    c = score.shape[1]
    t = tgt.reshape(n, c, 4)
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if pvar.ndim == 2:  # per-prior variance rows (box_coder convention)
        v0, v1 = pvar[:, 0][:, None], pvar[:, 1][:, None]
        v2, v3 = pvar[:, 2][:, None], pvar[:, 3][:, None]
    else:               # flat [4] (reference box_decoder_and_assign_op.h)
        v0, v1, v2, v3 = pvar[0], pvar[1], pvar[2], pvar[3]
    dw = jnp.minimum(v2 * t[..., 2], clip)
    dh = jnp.minimum(v3 * t[..., 3], clip)
    cx = v0 * t[..., 0] * pw[:, None] + pcx[:, None]
    cy = v1 * t[..., 1] * ph[:, None] + pcy[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    dec = jnp.stack([cx - w / 2, cy - h / 2,
                     cx + w / 2 - 1, cy + h / 2 - 1], axis=-1)  # N,C,4
    if c > 1:
        mj = 1 + jnp.argmax(score[:, 1:], axis=1)
        assign = jnp.take_along_axis(
            dec, mj[:, None, None].repeat(4, -1), axis=1)[:, 0]
    else:
        assign = prior
    return {"DecodeBox": dec.reshape(n, c * 4),
            "OutputAssignBox": assign}


@register_op("distribute_fpn_proposals", differentiable=False)
def distribute_fpn_proposals(ctx):
    """reference detection/distribute_fpn_proposals_op.h: route each
    roi to FPN level floor(log2(sqrt(area)/refer_scale)+refer_level)
    clamped to [min_level, max_level]. Fixed-shape TPU design: each
    MultiFpnRois[i] is [N,4] with that level's rois packed to the top
    (stable original order) and zero padding; MultiLevelCounts gives
    the true per-level count; RestoreIndex[orig_i] = position of roi i
    in the by-level concatenation (reference restore semantics)."""
    rois = ctx.input("FpnRois")  # N,4
    min_l = ctx.attr("min_level", 2)
    max_l = ctx.attr("max_level", 5)
    ref_l = ctx.attr("refer_level", 4)
    ref_s = ctx.attr("refer_scale", 224)
    n = rois.shape[0]
    num_level = max_l - min_l + 1
    # BBoxArea(..., normalized=false): +1 pixel offset on both sides
    # (reference distribute_fpn_proposals_op.h:85)
    area = jnp.maximum(rois[:, 2] - rois[:, 0] + 1, 0) * \
        jnp.maximum(rois[:, 3] - rois[:, 1] + 1, 0)
    scale = jnp.sqrt(area)
    lvl = jnp.floor(jnp.log2(jnp.maximum(scale, 1e-6) / ref_s) + ref_l)
    lvl = jnp.clip(lvl, min_l, max_l).astype(jnp.int32)
    orig = jnp.arange(n)
    # stable by-level order = sort key (level, original index)
    order = jnp.argsort(lvl * (n + 1) + orig)
    restore = jnp.argsort(order).astype(jnp.int32)  # orig -> shuffled pos
    outs, counts = [], []
    for i in range(num_level):
        l = min_l + i
        is_l = lvl == l
        # rows of level l packed to the top, zero padding below
        key = jnp.where(is_l, orig, n + orig)
        perm = jnp.argsort(key)
        packed = rois[perm] * is_l[perm][:, None].astype(rois.dtype)
        outs.append(packed)
        counts.append(jnp.sum(is_l).astype(jnp.int32))
    return {"MultiFpnRois": outs,
            "MultiLevelCounts": jnp.stack(counts),
            "RestoreIndex": restore.reshape(n, 1)}


@register_op("roi_perspective_transform", differentiable=False)
def roi_perspective_transform(ctx):
    """reference detection/roi_perspective_transform_op.cc: each roi is
    a quad (8 coords, clockwise from top-left); estimate its aspect,
    build the 3x3 projective map from output grid to input coords, and
    bilinear-sample X inside the quad (0 outside). Single-image X
    [1,C,H,W] (same convention as roi_pool/roi_align here,
    misc_ops.py)."""
    x = ctx.input("X")        # 1,C,H,W
    rois = ctx.input("ROIs")  # N,8
    th = ctx.attr("transformed_height", 8)
    tw = ctx.attr("transformed_width", 8)
    sscale = ctx.attr("spatial_scale", 1.0)
    _, ch, hh, ww = x.shape
    feat = x[0]

    rx = rois[:, 0::2] * sscale  # N,4
    ry = rois[:, 1::2] * sscale

    def matrix(roi_x, roi_y):
        x0, x1, x2, x3 = roi_x
        y0, y1, y2, y3 = roi_y
        len1 = jnp.hypot(x0 - x1, y0 - y1)
        len2 = jnp.hypot(x1 - x2, y1 - y2)
        len3 = jnp.hypot(x2 - x3, y2 - y3)
        len4 = jnp.hypot(x3 - x0, y3 - y0)
        est_h = (len2 + len4) / 2.0
        est_w = (len1 + len3) / 2.0
        nh = th
        nw_f = jnp.round(est_w * (nh - 1) / jnp.maximum(est_h, 1e-6)) + 1
        nw = jnp.minimum(nw_f, tw)
        dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
        dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
        den = dx1 * dy2 - dx2 * dy1
        den = jnp.where(jnp.abs(den) < 1e-12, 1e-12, den)
        m6 = (dx3 * dy2 - dx2 * dy3) / den / jnp.maximum(nw - 1, 1)
        m7 = (dx1 * dy3 - dx3 * dy1) / den / jnp.maximum(nh - 1, 1)
        m3 = (y1 - y0 + m6 * (nw - 1) * y1) / jnp.maximum(nw - 1, 1)
        m4 = (y3 - y0 + m7 * (nh - 1) * y3) / jnp.maximum(nh - 1, 1)
        m0 = (x1 - x0 + m6 * (nw - 1) * x1) / jnp.maximum(nw - 1, 1)
        m1 = (x3 - x0 + m7 * (nh - 1) * x3) / jnp.maximum(nh - 1, 1)
        return m0, m1, x0, m3, m4, y0, m6, m7

    def in_quad(px, py, roi_x, roi_y):
        # ray-casting even-odd rule, vectorized over the grid; the
        # reference additionally counts points within 1e-4 of any edge
        # as inside (in_quad's first loop) -- mirrored here with a
        # point-to-segment distance test
        xa, ya = roi_x, roi_y
        xb = jnp.roll(roi_x, -1)
        yb = jnp.roll(roi_y, -1)
        crosses = ((ya[:, None, None] > py[None]) !=
                   (yb[:, None, None] > py[None])) & \
            (px[None] < (xb - xa)[:, None, None] *
             (py[None] - ya[:, None, None]) /
             (yb - ya + 1e-12)[:, None, None] + xa[:, None, None])
        inside = jnp.sum(crosses.astype(jnp.int32), axis=0) % 2 == 1
        ex = (xb - xa)[:, None, None]
        ey = (yb - ya)[:, None, None]
        dx = px[None] - xa[:, None, None]
        dy = py[None] - ya[:, None, None]
        t = jnp.clip((dx * ex + dy * ey) /
                     jnp.maximum(ex * ex + ey * ey, 1e-12), 0.0, 1.0)
        dist2 = (dx - t * ex) ** 2 + (dy - t * ey) ** 2
        on_edge = jnp.any(dist2 < 1e-8, axis=0)
        return inside | on_edge

    gy, gx = jnp.mgrid[0:th, 0:tw]

    def one(roi_x, roi_y):
        m0, m1, m2, m3, m4, m5, m6, m7 = matrix(roi_x, roi_y)
        wgt = m6 * gx + m7 * gy + 1.0
        in_w = (m0 * gx + m1 * gy + m2) / wgt
        in_h = (m3 * gx + m4 * gy + m5) / wgt
        inside = in_quad(in_w, in_h, roi_x, roi_y) & \
            (in_w >= -0.5) & (in_w <= ww - 0.5) & \
            (in_h >= -0.5) & (in_h <= hh - 0.5)
        sw = jnp.clip(in_w, 0, ww - 1)
        sh = jnp.clip(in_h, 0, hh - 1)
        x0i = jnp.floor(sw).astype(jnp.int32)
        y0i = jnp.floor(sh).astype(jnp.int32)
        x1i = jnp.minimum(x0i + 1, ww - 1)
        y1i = jnp.minimum(y0i + 1, hh - 1)
        ax = sw - x0i
        ay = sh - y0i
        v = (feat[:, y0i, x0i] * (1 - ay) * (1 - ax)
             + feat[:, y0i, x1i] * (1 - ay) * ax
             + feat[:, y1i, x0i] * ay * (1 - ax)
             + feat[:, y1i, x1i] * ay * ax)
        return jnp.where(inside[None], v, 0.0)

    return {"Out": jax.vmap(one)(rx, ry)}


def _rasterize_masks_np(rois, labels, gt_boxes, polys,
                        poly_len, num_classes, resolution):
    """Host-side mask-target rasterization (numpy): for each fg roi,
    take the polygon of its best-IoU gt and rasterize it (even-odd
    rule) onto a resolution x resolution grid over the roi extent,
    written into the class-th mask slab."""
    r = rois.shape[0]
    m = resolution
    masks = np.zeros((r, num_classes * m * m), np.int32)
    has = np.zeros((r,), np.int32)
    for i in range(r):
        cls = int(labels[i])
        if cls <= 0:
            continue
        # best gt by IoU
        x1, y1, x2, y2 = rois[i]
        ious = []
        for g in range(gt_boxes.shape[0]):
            gx1, gy1, gx2, gy2 = gt_boxes[g]
            iw = max(min(x2, gx2) - max(x1, gx1), 0)
            ih = max(min(y2, gy2) - max(y1, gy1), 0)
            inter = iw * ih
            ua = max((x2 - x1) * (y2 - y1)
                     + (gx2 - gx1) * (gy2 - gy1) - inter, 1e-6)
            ious.append(inter / ua)
        if not ious:
            continue
        g = int(np.argmax(ious))
        npts = int(poly_len[g])
        if npts < 3:
            continue
        poly = polys[g, :npts]  # V,2
        has[i] = 1
        ys = y1 + (np.arange(m) + 0.5) * max(y2 - y1, 1e-6) / m
        xs = x1 + (np.arange(m) + 0.5) * max(x2 - x1, 1e-6) / m
        gx, gy = np.meshgrid(xs, ys)
        inside = np.zeros((m, m), bool)
        xa, ya = poly[:, 0], poly[:, 1]
        xb, yb = np.roll(xa, -1), np.roll(ya, -1)
        for e in range(npts):
            cond = ((ya[e] > gy) != (yb[e] > gy)) & \
                (gx < (xb[e] - xa[e]) * (gy - ya[e])
                 / (yb[e] - ya[e] + 1e-12) + xa[e])
            inside ^= cond
        slab = masks[i].reshape(num_classes, m, m)
        slab[cls] = inside.astype(np.int32)
        masks[i] = slab.reshape(-1)
    return masks, has


@register_op("generate_mask_labels", differentiable=False,
             host_effect=True)
def generate_mask_labels(ctx):
    """reference detection/generate_mask_labels_op.cc (Mask R-CNN mask
    targets). TPU design: polygon rasterization is inherently
    host-side (the reference does it on CPU too); runs as an ordered
    io_callback with fixed shapes. Inputs use the padded batch design:
    Rois [R,4], LabelsInt32 [R], GtSegms [G,V,2] one polygon per gt
    padded to V points with PolyLen [G], GtBoxes/GtClasses [G,...]."""
    from jax.experimental import io_callback

    rois = ctx.input("Rois")
    labels = ctx.input("LabelsInt32")
    gt_boxes = ctx.input("GtBoxes")
    polys = ctx.input("GtSegms")
    poly_len = ctx.input("PolyLen")
    num_classes = ctx.attr("num_classes", 81)
    resolution = ctx.attr("resolution", 14)
    r = rois.shape[0]

    # GtClasses is accepted for interface parity but the mask slab is
    # keyed off the roi's own label (as the roi/label pairing already
    # encodes the class); it is not shipped through the callback.
    def _host(ro, la, gb, po, pl):
        return _rasterize_masks_np(
            np.asarray(ro), np.asarray(la), np.asarray(gb),
            np.asarray(po), np.asarray(pl),
            num_classes, resolution)

    masks, has = io_callback(
        _host,
        (jax.ShapeDtypeStruct((r, num_classes * resolution * resolution),
                              np.int32),
         jax.ShapeDtypeStruct((r,), np.int32)),
        rois, labels, gt_boxes, polys, poly_len,
        ordered=True)
    return {"MaskRois": rois, "RoiHasMaskInt32": has,
            "MaskInt32": masks}
