"""Op-gap closure, batch 3: the fused-op family, tensor-utility ops,
and executor-parity ops.

Parity targets (reference paddle/fluid/operators/): fill_op.cc,
operators/distributed_ops/fake_init_op.cc, controlflow/get_places_op.cc,
delete_var_op.cc, controlflow/feed_op.cc, controlflow/fetch_op.cc,
alloc_continuous_space_op.cc, cross_entropy_op.cc (cross_entropy2),
similarity_focus_op.cc, tree_conv_op.cc + math/tree2col.cc,
fused/fused_elemwise_activation_op.cc, fused/fusion_squared_mat_sub_op.cc,
fused/fusion_repeated_fc_relu_op.cc, fused/fusion_seqconv_eltadd_relu_op.cc,
fused/fusion_seqpool_concat_op.cc, fused/fusion_seqexpand_concat_fc_op.cc,
fused/fusion_transpose_flatten_concat_op.cc, fused/fusion_gru_op.cc,
fused/fusion_lstm_op.cc, fused/fused_embedding_fc_lstm_op.cc,
fused/fused_embedding_seq_pool_op.cc, attention_lstm_op.cc,
conv_fusion_op.cc, fused/fusion_conv_inception_op.cu,
reader/create_custom_reader_op.cc, reader/read_op.cc.

TPU design note: the reference's fused CPU/cuDNN kernels exist because
its per-op interpreter cannot fuse across op boundaries; under XLA the
unfused composition compiles to the same fused HLO, so these kernels
are *compositions* of the already-registered primitives -- they exist
for program-level API parity (a reference program mentioning
fusion_gru must load and run), not for speed.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


# --------------------------------------------------------------------------
# tensor utility / executor-parity ops
# --------------------------------------------------------------------------
@register_op("fill", differentiable=False)
def fill(ctx):
    """reference fill_op.cc: materialize attr `value` (row-major flat
    float list) into a tensor of attr `shape`/`dtype`."""
    from ..core.types import to_np_dtype

    shape = [int(s) for s in ctx.attr("shape", [])]
    dtype = to_np_dtype(ctx.attr("dtype", "float32"))
    vals = np.asarray(ctx.attr("value", []), dtype=np.float64)
    return {"Out": jnp.asarray(vals.reshape(shape).astype(dtype))}


@register_op("fake_init", differentiable=False)
def fake_init(ctx):
    """reference distributed_ops/fake_init_op.cc: placeholder init for
    vars whose real storage lives on a remote pserver (distributed
    lookup tables) -- allocates shape but writes nothing. Here: zeros,
    since XLA buffers cannot be left uninitialized."""
    shape = [int(s) for s in ctx.attr("shape", [])]
    return {"Out": jnp.zeros(shape, jnp.float32)}


@register_op("delete_var", differentiable=False)
def delete_var(ctx):
    """reference delete_var_op.cc: drop vars from the scope. Under XLA
    buffer liveness is compiler-managed (VERDICT row 2): inside a
    compiled block this is a no-op marker; the executor additionally
    drops the named vars from the scope after the step (see
    core/executor.py handling of delete_var)."""
    return {}


@register_op("get_places", differentiable=False)
def get_places(ctx):
    """reference controlflow/get_places_op.cc: enumerate devices for
    ParallelDo-era programs. Returns the device ids of the current
    jax backend as an int32 vector (capped by attr device_count)."""
    n = ctx.attr("device_count", 0)
    try:
        avail = len(jax.devices())
    except Exception:
        avail = 1
    if not n:
        n = avail
    return {"Out": jnp.arange(min(int(n), avail), dtype=jnp.int32)}


@register_op("feed", differentiable=False)
def feed(ctx):
    """reference controlflow/feed_op.cc: copy column `col` of the feed
    holder into the target var. The executor short-circuits feed ops
    (core/executor.py _SKIP_OP_TYPES) and materializes feeds directly;
    this kernel exists so standalone run_op / program round-trips of
    reference programs behave (identity on X)."""
    return {"Out": ctx.input("X")}


@register_op("fetch", differentiable=False)
def fetch(ctx):
    """reference controlflow/fetch_op.cc: copy var into fetch holder
    column `col`. Executor short-circuits; identity for parity."""
    return {"Out": ctx.input("X")}


@register_op("alloc_continuous_space", differentiable=False)
def alloc_continuous_space(ctx):
    """reference alloc_continuous_space_op.cc: coalesce a list of
    params/grads into one contiguous fused buffer (gradient coalescing
    for fused allreduce). XLA performs buffer coalescing itself; this
    op keeps the program-level contract: FusedOutput = flat concat,
    Output[i] = view reshaped back to the input shapes."""
    xs = ctx.inputs("Input")
    const = ctx.attr("constant", None)
    set_const = ctx.attr("set_constant", False)
    flat = [jnp.ravel(x) for x in xs]
    fused = jnp.concatenate(flat) if flat else jnp.zeros((0,), jnp.float32)
    if set_const and const is not None:
        fused = jnp.full_like(fused, const)
    outs = []
    off = 0
    for x in xs:
        n = int(np.prod(x.shape)) if x.ndim else 1
        outs.append(jnp.reshape(fused[off:off + n], x.shape))
        off += n
    return {"Output": outs, "FusedOutput": fused}


@register_op("cross_entropy2", stop_gradient_slots=("Label",))
def cross_entropy2(ctx):
    """reference cross_entropy_op.cc CrossEntropyOp2: hard-label CE
    that also emits MatchX (the matched probability, reused by the
    grad) and XShape (LoD carrier). ignore_index rows produce 0."""
    x = ctx.input("X")
    label = ctx.input("Label")
    ignore = ctx.attr("ignore_index", -100)
    lbl = label.reshape(label.shape[:-1]) if label.shape[-1:] == (1,) \
        else label
    lbl_i = lbl.astype(jnp.int32)
    valid = (lbl_i != ignore)
    safe = jnp.where(valid, lbl_i, 0)
    match_x = jnp.take_along_axis(x, safe[..., None], axis=-1)
    eps = jnp.finfo(x.dtype).tiny
    y = -jnp.log(jnp.maximum(match_x, eps))
    y = jnp.where(valid[..., None], y, 0.0)
    return {"Y": y, "MatchX": match_x,
            "XShape": jnp.zeros(x.shape + (0,), x.dtype)}


@register_op("similarity_focus", differentiable=False)
def similarity_focus(ctx):
    """reference similarity_focus_op.cc: for each (batch, index in
    `indexes`) take the HxW slice at channel axis position, greedily
    pick min(H,W) maxima such that no two share a row or column, OR
    the resulting masks over all indexes, broadcast across channels."""
    x = ctx.input("X")  # N,A,B,C with axis selecting one of dims 1..3
    axis = ctx.attr("axis", 1)
    indexes = [int(i) for i in ctx.attr("indexes", [0])]
    if axis != 1:
        # move the focus axis to position 1 (reference supports 1..3)
        x_m = jnp.moveaxis(x, axis, 1)
    else:
        x_m = x
    n, a, b, c = x_m.shape
    k = min(b, c)

    def one_index(t):  # t: N,B,C
        def body(i, carry):
            mask, rowused, colused = carry
            neg = jnp.finfo(t.dtype).min
            avail = jnp.where(rowused[:, :, None] | colused[:, None, :],
                              neg, t)
            flat = avail.reshape(n, -1)
            idx = jnp.argmax(flat, axis=1)
            r, cc = idx // c, idx % c
            mask = mask.at[jnp.arange(n), r, cc].set(1.0)
            rowused = rowused.at[jnp.arange(n), r].set(True)
            colused = colused.at[jnp.arange(n), cc].set(True)
            return mask, rowused, colused

        init = (jnp.zeros((n, b, c), x.dtype),
                jnp.zeros((n, b), bool), jnp.zeros((n, c), bool))
        mask, _, _ = lax.fori_loop(0, k, body, init)
        return mask

    total = jnp.zeros((n, b, c), x.dtype)
    for i in indexes:
        total = jnp.maximum(total, one_index(x_m[:, i]))
    out = jnp.broadcast_to(total[:, None], (n, a, b, c))
    if axis != 1:
        out = jnp.moveaxis(out, 1, axis)
    return {"Out": out}


# --------------------------------------------------------------------------
# tree_conv (reference tree_conv_op.cc + math/tree2col.cc)
# --------------------------------------------------------------------------
def _tree_patch_weights(edges, n_nodes, max_depth):
    """Per-root eta weights, vectorized form of Tree2ColUtil.

    edges: [E,2] int32 1-indexed (u -> v child edge; 0,0 padding).
    Returns (eta_l, eta_r, eta_t): each [n_nodes, n_nodes] where row r
    holds the weights of every node in root r's patch (0 = absent).
    Formulas mirror math/tree2col.h TreeNode::eta_{t,l,r}: with
    depth d (root=0), child position idx (1-based) among pclen
    siblings: eta_t=(md-d)/md, eta_l=(1-eta_t)*((idx-1)/(pclen-1) or
    .5 when pclen==1), eta_r=(1-eta_t)*(1-eta_l_frac_part)."""
    e_u, e_v = edges[:, 0], edges[:, 1]
    ok = (e_u > 0) & (e_v > 0)
    nn = n_nodes + 1  # 1-indexed with 0 = null

    # parent pointer + child position (idx) + sibling count (pclen)
    parent = jnp.zeros((nn,), jnp.int32)
    parent = parent.at[jnp.where(ok, e_v, 0)].set(
        jnp.where(ok, e_u, 0).astype(jnp.int32))
    # child position: order of appearance among edges of the same u
    same_u = (e_u[:, None] == e_u[None, :]) & ok[:, None] & ok[None, :]
    before = jnp.tril(jnp.ones_like(same_u), k=-1)
    pos = jnp.sum(same_u & before.astype(bool), axis=1) + 1  # 1-based
    childpos = jnp.zeros((nn,), jnp.int32).at[
        jnp.where(ok, e_v, 0)].set(jnp.where(ok, pos, 0).astype(jnp.int32))
    nchild = jnp.zeros((nn,), jnp.int32).at[
        jnp.where(ok, e_u, 0)].add(jnp.where(ok, 1, 0).astype(jnp.int32))
    pclen = nchild[parent]  # siblings of each node

    # depth of v relative to root r: follow parent chain <= max_depth-1
    # hops; anc[k] = k-th ancestor of v
    roots = jnp.arange(nn, dtype=jnp.int32)
    depth = jnp.full((nn, nn), -1, jnp.int32)  # [root, node]
    anc = jnp.arange(nn, dtype=jnp.int32)
    for d in range(max_depth):
        hit = (anc[None, :] == roots[:, None]) & (anc[None, :] > 0)
        depth = jnp.where(hit & (depth < 0), d, depth)
        anc = parent[anc]
    in_patch = depth >= 0

    md = float(max_depth)
    d_f = depth.astype(jnp.float32)
    eta_t = jnp.where(in_patch, (md - d_f) / md, 0.0)
    is_root = roots[:, None] == jnp.arange(nn)[None, :]
    idx = jnp.where(is_root, 1, childpos[None, :]).astype(jnp.float32)
    pc = jnp.where(is_root, 1, pclen[None, :]).astype(jnp.float32)
    frac = jnp.where(pc == 1, 0.5, (idx - 1.0) / jnp.maximum(pc - 1.0, 1.0))
    eta_l = jnp.where(in_patch, (1.0 - eta_t) * frac, 0.0)
    eta_r = jnp.where(in_patch, (1.0 - eta_t) * (1.0 - frac), 0.0)
    eta_t = jnp.where(in_patch, eta_t, 0.0)
    return eta_l[1:, 1:], eta_r[1:, 1:], eta_t[1:, 1:]


@register_op("tree_conv", stop_gradient_slots=("EdgeSet",))
def tree_conv(ctx):
    """reference tree_conv_op.cc: tree-based convolution (TBCNN,
    arxiv 1409.5718). NodesVector [B,N,F], EdgeSet [B,E,2] (1-indexed
    parent->child, zero padded), Filter [F,3,S,M] where the 3 taps are
    (left, right, top) eta-weighted patch sums. Out [B,N,S,M]."""
    edges = ctx.input("EdgeSet").astype(jnp.int32)
    feats = ctx.input("NodesVector")
    filt = ctx.input("Filter")
    max_depth = ctx.attr("max_depth", 2)
    b, n, f = feats.shape
    fdim, three, s, m = filt.shape
    w = jnp.transpose(filt, (1, 0, 2, 3)).reshape(3 * fdim, s * m)

    def per_batch(e, x):
        eta_l, eta_r, eta_t = _tree_patch_weights(e, n, max_depth)
        # patch tap sums: [N roots, F] per tap; matches tree2col's
        # interleaved (F,3) layout via the (3,F) weight reshape above
        pl = eta_l @ x
        pr = eta_r @ x
        pt = eta_t @ x
        patch = jnp.concatenate([pl, pr, pt], axis=-1)  # N, 3F
        return (patch @ w).reshape(n, s, m)

    return {"Out": jax.vmap(per_batch)(edges, feats)}


# --------------------------------------------------------------------------
# fused elementwise + activation (reference fused_elemwise_activation_op.cc)
# --------------------------------------------------------------------------
_UNARY = {
    "relu": jax.nn.relu,
    "scale": None,  # needs attr
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
}
_BINARY = {
    "elementwise_add": jnp.add,
    "elementwise_mul": jnp.multiply,
}


@register_op("fused_elemwise_activation")
def fused_elemwise_activation(ctx):
    """reference fused/fused_elemwise_activation_op.cc: compose two
    functors from functor_list -- Unary(Binary(X,Y)) when the second
    entry is binary, else Binary(X, Unary(Y)). Supported unaries:
    scale (attr `scale`), relu; binaries: elementwise_add/mul with
    axis-style broadcast on Y."""
    x = ctx.input("X")
    y = ctx.input("Y")
    functors = list(ctx.attr("functor_list", []))
    axis = ctx.attr("axis", -1)
    if len(functors) != 2:
        raise ValueError("fused_elemwise_activation: functor_list "
                         "must hold exactly 2 functor names")

    def bcast_y(yv, like):
        if yv.ndim == like.ndim:
            return yv
        ax = axis if axis >= 0 else like.ndim - yv.ndim
        shape = [1] * like.ndim
        for i, d in enumerate(yv.shape):
            shape[ax + i] = d
        return jnp.reshape(yv, shape)

    def apply_unary(name, v):
        if name == "scale":
            return v * ctx.attr("scale", 1.0)
        fn = _UNARY.get(name)
        if fn is None:
            raise ValueError(f"fused_elemwise_activation: unsupported "
                             f"unary functor {name!r}")
        return fn(v)

    f0, f1 = functors
    if f1 in _BINARY:       # Unary(Binary(X, Y))
        inter = _BINARY[f1](x, bcast_y(y, x))
        out = apply_unary(f0, inter)
    elif f0 in _BINARY:     # Binary(X, Unary(Y))
        inter = apply_unary(f1, y)
        out = _BINARY[f0](x, bcast_y(inter, x))
    else:
        raise ValueError(f"fused_elemwise_activation: functor_list "
                         f"{functors} has no supported binary functor")
    return {"Out": out, "IntermediateOut": inter}


@register_op("fusion_squared_mat_sub")
def fusion_squared_mat_sub(ctx):
    """reference fused/fusion_squared_mat_sub_op.cc:
    Out = ((X@Y)^2 - (X^2)@(Y^2)) * scalar."""
    x = ctx.input("X")
    y = ctx.input("Y")
    scalar = ctx.attr("scalar", 1.0)
    sx = x * x
    sy = y * y
    sxy = jnp.matmul(x, y)
    sxy2 = sxy * sxy
    out = (sxy2 - jnp.matmul(sx, sy)) * scalar
    return {"SquaredX": sx, "SquaredY": sy, "SquaredXY": sxy2, "Out": out}


@register_op("fusion_repeated_fc_relu")
def fusion_repeated_fc_relu(ctx):
    """reference fused/fusion_repeated_fc_relu_op.cc: N stacked
    fc+relu stages; W/Bias are parallel input lists."""
    x = ctx.input("X")
    ws = ctx.inputs("W")
    bs = ctx.inputs("Bias")
    if not ws:
        raise ValueError("fusion_repeated_fc_relu: W list is empty")
    relus = []
    h = x
    for i, w in enumerate(ws):
        b = bs[i] if i < len(bs) else None
        h = jnp.matmul(h, jnp.reshape(w, (h.shape[-1], -1)))
        if b is not None:
            h = h + jnp.reshape(b, (1, -1))
        h = jax.nn.relu(h)
        relus.append(h)
    return {"ReluOut": relus[:-1], "Out": relus[-1]}


def _sub_ctx(ctx, op_type, inputs, attrs):
    """Build an OpContext for calling another registered kernel fn."""
    from ..core.registry import OpContext
    from ..core.program import Operator

    op = Operator(ctx.op.block, type=op_type,
                  inputs={}, outputs={}, attrs=attrs)
    return OpContext(op, {k: [v] for k, v in inputs.items()})


@register_op("fusion_seqconv_eltadd_relu", stop_gradient_slots=("SeqLen",))
def fusion_seqconv_eltadd_relu(ctx):
    """reference fused/fusion_seqconv_eltadd_relu_op.cc:
    sequence_conv + bias add + relu in one op."""
    from .sequence_ops import sequence_conv

    b = ctx.input("Bias")
    sub = _sub_ctx(ctx, "sequence_conv",
                   {"X": ctx.input("X"), "Filter": ctx.input("Filter"),
                    "SeqLen": ctx.input("SeqLen")},
                   {"contextLength": ctx.attr("contextLength", 3),
                    "contextStart": ctx.attr("contextStart", 0)})
    out = sequence_conv(sub)
    if isinstance(out, dict):
        out = out.get("Out", next(iter(out.values())))
    colmat = out
    return {"Out": jax.nn.relu(colmat + jnp.reshape(b, (1, 1, -1))),
            "ColMat": colmat}


@register_op("fusion_seqpool_concat", stop_gradient_slots=("SeqLen",))
def fusion_seqpool_concat(ctx):
    """reference fused/fusion_seqpool_concat_op.cc: SUM/AVERAGE/SQRT
    sequence_pool over each input then concat on axis 1."""
    xs = ctx.inputs("X")
    lens = ctx.inputs("SeqLen")
    ptype = ctx.attr("pooltype", "SUM").upper()
    pooled = []
    for i, x in enumerate(xs):
        sl = lens[i] if i < len(lens) and lens[i] is not None else \
            jnp.full((x.shape[0],), x.shape[1], jnp.int32)
        m = (jnp.arange(x.shape[1])[None, :] < sl[:, None]).astype(x.dtype)
        summed = jnp.sum(x * m[..., None], axis=1)
        denom = jnp.maximum(sl.astype(x.dtype), 1)[:, None]
        if ptype == "AVERAGE":
            summed = summed / denom
        elif ptype == "SQRT":
            summed = summed / jnp.sqrt(denom)
        pooled.append(summed)
    return {"Out": jnp.concatenate(pooled, axis=1)}


@register_op("fusion_seqexpand_concat_fc", stop_gradient_slots=("SeqLen",))
def fusion_seqexpand_concat_fc(ctx):
    """reference fused/fusion_seqexpand_concat_fc_op.cc: X[0] is the
    [B,T,D0] ref sequence; X[1:] are [B,Di] per-sequence vectors
    broadcast (seq_expand) along T; concat on the feature axis feeds
    one fc (+bias, activation)."""
    xs = ctx.inputs("X")
    w = ctx.input("FCWeight")
    b = ctx.input("FCBias")
    act = ctx.attr("fc_activation", "identity")
    ref = xs[0]
    bsz, t = ref.shape[0], ref.shape[1]
    cols = [ref]
    for x in xs[1:]:
        cols.append(jnp.broadcast_to(x[:, None, :],
                                     (bsz, t, x.shape[-1])))
    cat = jnp.concatenate(cols, axis=-1)
    out = jnp.einsum("btd,dm->btm",
                     cat, jnp.reshape(w, (cat.shape[-1], -1)))
    if b is not None:
        out = out + jnp.reshape(b, (1, 1, -1))
    if act == "relu":
        out = jax.nn.relu(out)
    elif act == "tanh":
        out = jnp.tanh(out)
    elif act not in ("identity", "", None):
        raise ValueError(f"fusion_seqexpand_concat_fc: unsupported "
                         f"activation {act!r}")
    return {"Out": out, "FCOut": out}


@register_op("fusion_transpose_flatten_concat", differentiable=False)
def fusion_transpose_flatten_concat(ctx):
    """reference fused/fusion_transpose_flatten_concat_op.cc: per
    input transpose(trans_axis) then flatten(flatten_axis) then
    concat(concat_axis)."""
    xs = ctx.inputs("X")
    trans = [int(a) for a in ctx.attr("trans_axis", [])]
    flat_axis = ctx.attr("flatten_axis", 1)
    cat_axis = ctx.attr("concat_axis", 1)
    outs = []
    for x in xs:
        t = jnp.transpose(x, trans) if trans else x
        lead = int(np.prod(t.shape[:flat_axis])) if flat_axis else 1
        outs.append(jnp.reshape(t, (lead, -1)))
    return {"Out": jnp.concatenate(outs, axis=cat_axis)}


# --------------------------------------------------------------------------
# fused recurrent ops: compositions over the registered gru/lstm kernels
# --------------------------------------------------------------------------
@register_op("fusion_gru", stop_gradient_slots=("SeqLen",))
def fusion_gru(ctx):
    """reference fused/fusion_gru_op.cc: XX = X@WeightX (+bias), then
    the gru recurrence with WeightH. X [B,T,M], WeightX [M,3D],
    WeightH [D,3D], Bias [1,3D]. Batched aux outputs (ReorderedH0,
    BatchedInput, BatchedOut) are artifacts of the reference's
    LoD-batching; here XX doubles for BatchedInput."""
    from .rnn_ops import gru as gru_kernel

    x = ctx.input("X")
    wx = ctx.input("WeightX")
    wh = ctx.input("WeightH")
    bias = ctx.input("Bias")
    xx = jnp.einsum("btm,md->btd", x, wx)
    sub = _sub_ctx(ctx, "gru",
                   {"Input": xx, "Weight": wh, "Bias": bias,
                    "SeqLen": ctx.input("SeqLen"), "H0": ctx.input("H0")},
                   {"is_reverse": ctx.attr("is_reverse", False),
                    "origin_mode": ctx.attr("origin_mode", False),
                    "gate_activation": ctx.attr("gate_activation",
                                                "sigmoid"),
                    "activation": ctx.attr("activation", "tanh")})
    hidden = gru_kernel(sub)["Hidden"]
    return {"Hidden": hidden, "XX": xx, "BatchedInput": xx,
            "BatchedOut": hidden,
            "ReorderedH0": ctx.input("H0") if ctx.input("H0") is not None
            else jnp.zeros((x.shape[0], wh.shape[0]), x.dtype)}


@register_op("fusion_lstm", stop_gradient_slots=("SeqLen",))
def fusion_lstm(ctx):
    """reference fused/fusion_lstm_op.cc: XX = X@WeightX, then the
    lstm recurrence with WeightH. Bias [1,4D(+3D peepholes)]."""
    from .rnn_ops import lstm as lstm_kernel

    x = ctx.input("X")
    wx = ctx.input("WeightX")
    wh = ctx.input("WeightH")
    xx = jnp.einsum("btm,md->btd", x, wx)
    sub = _sub_ctx(ctx, "lstm",
                   {"Input": xx, "Weight": wh, "Bias": ctx.input("Bias"),
                    "SeqLen": ctx.input("SeqLen"),
                    "H0": ctx.input("H0"), "C0": ctx.input("C0")},
                   {"use_peepholes": ctx.attr("use_peepholes", False),
                    "is_reverse": ctx.attr("is_reverse", False),
                    "gate_activation": ctx.attr("gate_activation",
                                                "sigmoid"),
                    "cell_activation": ctx.attr("cell_activation", "tanh"),
                    "candidate_activation":
                        ctx.attr("candidate_activation", "tanh")})
    outs = lstm_kernel(sub)
    return {"Hidden": outs["Hidden"], "Cell": outs["Cell"], "XX": xx,
            "BatchedInput": xx, "BatchedHidden": outs["Hidden"],
            "BatchedCell": outs["Cell"]}


@register_op("fused_embedding_fc_lstm", stop_gradient_slots=("Ids",
                                                             "SeqLen"))
def fused_embedding_fc_lstm(ctx):
    """reference fused/fused_embedding_fc_lstm_op.cc: Embeddings holds
    the table already multiplied through the fc weight (rows are
    per-token pre-gate activations [V,4D]); lookup then lstm."""
    from .rnn_ops import lstm as lstm_kernel

    ids = ctx.input("Ids")
    table = ctx.input("Embeddings")
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    xx = jnp.take(table, ids.astype(jnp.int32), axis=0)  # B,T,4D
    sub = _sub_ctx(ctx, "lstm",
                   {"Input": xx, "Weight": ctx.input("WeightH"),
                    "Bias": ctx.input("Bias"),
                    "SeqLen": ctx.input("SeqLen"),
                    "H0": ctx.input("H0"), "C0": ctx.input("C0")},
                   {"use_peepholes": ctx.attr("use_peepholes", False),
                    "is_reverse": ctx.attr("is_reverse", False),
                    "gate_activation": ctx.attr("gate_activation",
                                                "sigmoid"),
                    "cell_activation": ctx.attr("cell_activation", "tanh"),
                    "candidate_activation":
                        ctx.attr("candidate_activation", "tanh")})
    outs = lstm_kernel(sub)
    return {"Hidden": outs["Hidden"], "Cell": outs["Cell"], "XX": xx}


@register_op("fused_embedding_seq_pool", stop_gradient_slots=("Ids",
                                                              "SeqLen"))
def fused_embedding_seq_pool(ctx):
    """reference fused/fused_embedding_seq_pool_op.cc: lookup_table +
    sum sequence_pool in one op. Ids [B,T(,1)], W [V,D] -> Out [B,D]."""
    ids = ctx.input("Ids")
    w = ctx.input("W")
    seq_len = ctx.input("SeqLen")
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    emb = jnp.take(w, ids.astype(jnp.int32), axis=0)  # B,T,D
    if seq_len is None:
        seq_len = jnp.full((ids.shape[0],), ids.shape[1], jnp.int32)
    m = (jnp.arange(ids.shape[1])[None, :]
         < seq_len[:, None]).astype(emb.dtype)
    return {"Out": jnp.sum(emb * m[..., None], axis=1)}


@register_op("attention_lstm", stop_gradient_slots=("SeqLen",))
def attention_lstm(ctx):
    """reference attention_lstm_op.cc: per step t --
    fcout = relu(concat(x, expand(c_{t-1})) @ AttentionWeight + b);
    optionally scaled (AttentionScalar) + bias + relu; softmax over
    the sequence; lstm_x = sum(softmax * x); one LSTM step on
    [lstm_x, h_{t-1}] @ LSTMWeight. Gate order i,f,c,o; candidate
    activation attr `candidate_activation`."""
    x = ctx.input("X")            # B,T,M
    c0 = ctx.input("C0")          # B,D
    h0 = ctx.input("H0")
    aw = ctx.input("AttentionWeight")          # (M+D),1
    ab = ctx.input("AttentionBias")            # 1,1 or None
    ascal = ctx.input("AttentionScalar")       # 1,1 or None
    ascal_b = ctx.input("AttentionScalarBias")
    lw = ctx.input("LSTMWeight")  # (D+M),4D
    lb = ctx.input("LSTMBias")    # 1,4D
    seq_len = ctx.input("SeqLen")
    from .rnn_ops import _ACT

    act_gate = _ACT[ctx.attr("gate_activation", "sigmoid")]
    act_cell = _ACT[ctx.attr("cell_activation", "tanh")]
    act_cand = _ACT[ctx.attr("candidate_activation", "tanh")]
    b_sz, t, m = x.shape
    d = c0.shape[-1]
    if h0 is None:
        h0 = jnp.zeros_like(c0)
    if seq_len is None:
        seq_len = jnp.full((b_sz,), t, jnp.int32)
    mask = jnp.arange(t)[None, :] < seq_len[:, None]  # B,T
    aw_x, aw_c = aw[:m], aw[m:]

    def step(carry, _):
        h_prev, c_prev = carry
        # attention scores over the whole (masked) sequence
        sc = (jnp.einsum("btm,mo->bt", x, aw_x)
              + (c_prev @ aw_c)[:, 0][:, None])
        if ab is not None:
            sc = sc + ab.reshape(())
        sc = jax.nn.relu(sc)
        if ascal is not None:
            sc = sc * ascal.reshape(())
        if ascal_b is not None:
            sc = jax.nn.relu(sc + ascal_b.reshape(()))
        sc = jnp.where(mask, sc, jnp.finfo(x.dtype).min)
        p = jax.nn.softmax(sc, axis=1)
        lstm_x = jnp.einsum("bt,btm->bm", p, x)
        gates = (jnp.concatenate([lstm_x, h_prev], -1) @ lw
                 + lb.reshape(1, -1))
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        i = act_gate(gi)
        f = act_gate(gf)
        c = f * c_prev + i * act_cand(gc)
        o = act_gate(go)
        h = o * act_cell(c)
        return (h, c), (h, c)

    (h_t, c_t), (hs, cs) = lax.scan(step, (h0, c0), None, length=t)
    return {"Hidden": jnp.swapaxes(hs, 0, 1),
            "Cell": jnp.swapaxes(cs, 0, 1)}


# --------------------------------------------------------------------------
# fused convolutions
# --------------------------------------------------------------------------
@register_op("conv2d_fusion")
def conv2d_fusion(ctx):
    """reference conv_fusion_op.cc (cuDNN conv+bias+act(+residual)):
    Output = act(conv(Input, Filter) + Bias (+ ResidualData))."""
    from .nn_ops import conv2d as conv2d_kernel

    sub = _sub_ctx(ctx, "conv2d",
                   {"Input": ctx.input("Input"),
                    "Filter": ctx.input("Filter")},
                   {"strides": ctx.attr("strides", [1, 1]),
                    "paddings": ctx.attr("paddings", [0, 0]),
                    "dilations": ctx.attr("dilations", [1, 1]),
                    "groups": ctx.attr("groups", 1)})
    out = conv2d_kernel(sub)["Output"]
    bias = ctx.input("Bias")
    if bias is not None:
        out = out + jnp.reshape(bias, (1, -1, 1, 1))
    resid = ctx.input("ResidualData")
    if resid is not None:
        out = out + resid
    act = ctx.attr("activation", "relu")
    if act == "relu":
        out = jax.nn.relu(out)
    elif act == "sigmoid":
        out = jax.nn.sigmoid(out)
    elif act == "tanh":
        out = jnp.tanh(out)
    elif act not in ("identity", "", None):
        raise ValueError(f"conv2d_fusion: unsupported activation {act!r}")
    return {"Output": out}


@register_op("conv2d_inception_fusion")
def conv2d_inception_fusion(ctx):
    """reference fused/fusion_conv_inception_op.cu: a 4-filter fused
    inception cell. Dataflow (mirrors the cuDNN kernel's buffer plan):
      b0 = 1x1 conv(avg_pool3x3(x), F0)
      y1 = 1x1 conv(x, F1); first oc1 channels go to the output, the
           remaining 2*F2_in feed
      y2 = 3x3 grouped(2) conv(y1_tail, F2); first F2_out - F3_in
           channels go to the output, the tail feeds
      y3 = 3x3 conv(y2_tail, F3)
      Output = relu(concat([b0, y1_head, y2_head, y3], channel))
    with per-branch biases."""
    x = ctx.input("Input")
    filts = ctx.inputs("Filter")
    biases = ctx.inputs("Bias")
    if len(filts) != 4:
        raise ValueError("conv2d_inception_fusion expects 4 filters")

    def conv(v, w, groups=1, same=False):
        k = w.shape[2]
        pad = (k // 2, k // 2) if same or k > 1 else (0, 0)
        return lax.conv_general_dilated(
            v, w, window_strides=(1, 1), padding=[pad, pad],
            feature_group_count=groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def addb(v, b):
        return v + jnp.reshape(b, (1, -1, 1, 1)) if b is not None else v

    pooled = lax.reduce_window(
        x, 0.0, lax.add, (1, 1, 3, 3), (1, 1, 1, 1),
        [(0, 0), (0, 0), (1, 1), (1, 1)]) / 9.0
    b0 = addb(conv(pooled, filts[0]), biases[0] if biases else None)

    f2_in = filts[2].shape[1]
    f3_in = filts[3].shape[1]
    y1 = addb(conv(x, filts[1]), biases[1] if len(biases) > 1 else None)
    oc1 = filts[1].shape[0] - f2_in * 2
    y1_head, y1_tail = y1[:, :oc1], y1[:, oc1:]
    y2 = addb(conv(y1_tail, filts[2], groups=2, same=True),
              biases[2] if len(biases) > 2 else None)
    oc2 = filts[2].shape[0] - f3_in
    y2_head, y2_tail = y2[:, :oc2], y2[:, oc2:]
    y3 = addb(conv(y2_tail, filts[3], same=True),
              biases[3] if len(biases) > 3 else None)
    out = jnp.concatenate([b0, y1_head, y2_head, y3], axis=1)
    return {"Output": jax.nn.relu(out)}


# --------------------------------------------------------------------------
# reader ops (reference operators/reader/read_op.cc,
# create_custom_reader_op.cc) -- host bridge into the Python reader
# registry; shapes must be static (declared on the reader var).
# --------------------------------------------------------------------------
_HOST_READERS = {}


def register_host_reader(name, gen_factory):
    """Bind a reader var name to a host generator factory. Each call
    of the read op pulls the next batch (restarting on exhaustion)."""
    _HOST_READERS[name] = {"factory": gen_factory, "it": None}


@register_op("read", differentiable=False, host_effect=True)
def read_op(ctx):
    """reference reader/read_op.cc: pop the next batch from the reader
    bound to input Reader's var name. Runs as an ordered host callback
    (the TPU analogue of the blocking queue pop); attrs `shapes` (flat
    int list with -1 separators not supported -- per-output shapes come
    from the output vars) and `dtypes` fix the static result specs."""
    from jax.experimental import io_callback

    rname = ctx.op.input("Reader")[0]
    entry = _HOST_READERS.get(rname)
    if entry is None:
        raise KeyError(f"read: no host reader registered under "
                       f"{rname!r}; call register_host_reader first")
    block = ctx.op.block
    from ..core.types import to_np_dtype

    from jax import dtypes as _dtypes

    specs = []
    for n in ctx.op.output("Out"):
        var = block.var(n)
        dt = to_np_dtype(var.dtype if var.dtype is not None else "float32")
        # 64-bit callback specs need x64; canonicalize like jnp does
        dt = _dtypes.canonicalize_dtype(dt)
        specs.append(jax.ShapeDtypeStruct(tuple(var.shape), dt))

    def _next():
        if entry["it"] is None:
            entry["it"] = iter(entry["factory"]())
        try:
            batch = next(entry["it"])
        except StopIteration:
            entry["it"] = iter(entry["factory"]())
            batch = next(entry["it"])
        return tuple(np.asarray(b, dtype=s.dtype).reshape(s.shape)
                     for b, s in zip(batch, specs))

    vals = io_callback(_next, tuple(specs), ordered=True)
    return {"Out": list(vals)}


@register_op("create_custom_reader", differentiable=False,
             host_effect=True)
def create_custom_reader(ctx):
    """reference reader/create_custom_reader_op.cc: decorate an
    underlying reader with a preprocessing function. The reference
    runs a sub-block per batch; here the decoration is a host
    callable registered via register_host_reader -- this op re-binds
    the output reader name to the decorated generator."""
    src = ctx.op.input("UnderlyingReader")[0]
    dst = ctx.op.output("Out")[0]
    fn_id = ctx.attr("decorator_id", None)
    entry = _HOST_READERS.get(src)
    if entry is None:
        raise KeyError(f"create_custom_reader: underlying reader "
                       f"{src!r} not registered")
    deco = _resolve_py_func(fn_id, "create_custom_reader",
                            required=False)

    def factory():
        for batch in entry["factory"]():
            yield deco(batch) if deco is not None else batch

    register_host_reader(dst, factory)
    return {"Out": jnp.zeros((1,), jnp.float32)}


# --------------------------------------------------------------------------
# reader-op family (reference operators/reader/): each create_* op
# builds or decorates a host reader in the _HOST_READERS registry; the
# `read` op above pops batches through an ordered io_callback. The
# reference's C++ ReaderHolder chain (shuffle -> batch -> double-buffer
# wrapping a file/py reader, reader/reader_op_registry.cc) maps 1:1
# onto generator decoration here -- the TPU-side difference is that
# batches enter the compiled step through the io_callback host bridge
# instead of a blocking-queue LoDTensor holder.
# --------------------------------------------------------------------------
def _resolve_py_func(fn_id, who, required):
    """Look up a host_ops py_func id; raise on an invalid id instead of
    silently degrading to raw records."""
    if fn_id is None:
        if required:
            raise ValueError(f"{who}: a parser_id attr is required")
        return None
    from .host_ops import _PY_FUNC_REGISTRY

    if not (isinstance(fn_id, int)
            and 0 <= fn_id < len(_PY_FUNC_REGISTRY)):
        raise ValueError(f"{who}: parser/decorator id {fn_id!r} is not "
                         f"a registered py_func id")
    return _PY_FUNC_REGISTRY[fn_id]


def _scan_recordio(path, parser):
    """Yield (parsed) records from one recordio file, closing the
    native scanner on exhaustion OR early generator abandonment."""
    from .. import native

    scanner = native.RecordIOScanner(path)
    try:
        for rec in scanner:
            yield parser(rec) if parser is not None else (rec,)
    finally:
        scanner.close()


def _require_reader(name, who):
    entry = _HOST_READERS.get(name)
    if entry is None:
        raise KeyError(f"{who}: underlying reader {name!r} is not "
                       f"registered (register_host_reader / a "
                       f"create_* reader op must run first)")
    return entry


@register_op("create_py_reader", differentiable=False,
             host_effect=True)
def create_py_reader(ctx):
    """reference reader/create_py_reader_op.cc: reader fed by a Python
    generator through a blocking queue. Here the queue IS a PyReader
    instance registered via reader.PyReader.bind_reader_var (or any
    factory bound with register_host_reader under the Out name's
    `source` attr)."""
    src = ctx.attr("source", None)
    dst = ctx.op.output("Out")[0]
    if src is None:
        raise ValueError("create_py_reader: needs a `source` attr "
                         "naming a registered host reader")
    entry = _require_reader(src, "create_py_reader")
    register_host_reader(dst, entry["factory"])
    return {"Out": jnp.zeros((1,), jnp.float32)}


@register_op("create_recordio_file_reader", differentiable=False,
             host_effect=True)
def create_recordio_file_reader(ctx):
    """reference reader/create_recordio_file_reader_op.cc: stream
    records from a recordio file (native C++ scanner,
    native/src/recordio.cc). Records are raw bytes; attr
    `parser_id` may name a py_func (host_ops) that maps
    bytes -> tuple of arrays (e.g. a MultiSlotDataFeed line parser)."""
    filename = ctx.attr("filename", None)
    dst = ctx.op.output("Out")[0]
    parser = _resolve_py_func(ctx.attr("parser_id", None),
                              "create_recordio_file_reader",
                              required=False)

    def factory():
        yield from _scan_recordio(filename, parser)

    register_host_reader(dst, factory)
    return {"Out": jnp.zeros((1,), jnp.float32)}


@register_op("create_shuffle_reader", differentiable=False,
             host_effect=True)
def create_shuffle_reader(ctx):
    """reference reader/create_shuffle_reader-era decorator: buffered
    shuffle with `buffer_size` (readers.shuffle semantics)."""
    import random as _random

    src = ctx.op.input("UnderlyingReader")[0]
    dst = ctx.op.output("Out")[0]
    buf_size = int(ctx.attr("buffer_size", 512))
    seed = ctx.attr("seed", 0)
    entry = _require_reader(src, "create_shuffle_reader")
    # ONE engine shared across passes: re-seeding per factory() call
    # would replay the identical order every epoch (the reference
    # shuffle reader keeps its engine state across passes too)
    rng = _random.Random(seed or None)

    def factory():
        buf = []
        for item in entry["factory"]():
            buf.append(item)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf

    register_host_reader(dst, factory)
    return {"Out": jnp.zeros((1,), jnp.float32)}


@register_op("create_batch_reader", differentiable=False,
             host_effect=True)
def create_batch_reader(ctx):
    """reference reader/create_batch_reader-era decorator: stack
    `batch_size` samples (tuples of arrays) into batch arrays."""
    src = ctx.op.input("UnderlyingReader")[0]
    dst = ctx.op.output("Out")[0]
    bsz = int(ctx.attr("batch_size", 1))
    drop_last = bool(ctx.attr("drop_last", False))
    entry = _require_reader(src, "create_batch_reader")

    def factory():
        def emit(batch):
            return tuple(np.stack([b[i] for b in batch])
                         for i in range(len(batch[0])))

        batch = []
        for item in entry["factory"]():
            batch.append(item)
            if len(batch) == bsz:
                yield emit(batch)
                batch = []
        if batch and not drop_last:
            yield emit(batch)  # reference keeps the partial tail batch

    register_host_reader(dst, factory)
    return {"Out": jnp.zeros((1,), jnp.float32)}


@register_op("create_multi_pass_reader", differentiable=False,
             host_effect=True)
def create_multi_pass_reader(ctx):
    """reference reader/create_multi_pass_reader-era decorator: repeat
    the underlying reader `pass_num` times (multi-epoch training as
    one logical pass)."""
    src = ctx.op.input("UnderlyingReader")[0]
    dst = ctx.op.output("Out")[0]
    passes = int(ctx.attr("pass_num", 1))
    entry = _require_reader(src, "create_multi_pass_reader")

    def factory():
        for _ in range(passes):
            yield from entry["factory"]()

    register_host_reader(dst, factory)
    return {"Out": jnp.zeros((1,), jnp.float32)}


@register_op("create_double_buffer_reader", differentiable=False,
             host_effect=True)
def create_double_buffer_reader(ctx):
    """reference reader/create_double_buffer_reader_op.cc (async H2D
    staging, reader/buffered_reader.cc): a daemon thread prefetches
    into a bounded queue so host parsing overlaps device steps."""
    import queue as _queue
    import threading

    src = ctx.op.input("UnderlyingReader")[0]
    dst = ctx.op.output("Out")[0]
    depth = int(ctx.attr("buffer_size", 2))
    entry = _require_reader(src, "create_double_buffer_reader")

    def factory():
        q = _queue.Queue(maxsize=depth)
        DONE = object()
        stop = threading.Event()

        def put(item):
            # bounded put that gives up if the consumer abandoned the
            # generator (otherwise the fill thread blocks forever)
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def fill():
            try:
                for item in entry["factory"]():
                    if not put(item):
                        return
                put(DONE)
            except BaseException as e:  # surfaced to the consumer --
                # swallowing it would silently truncate the epoch
                put(e)

        threading.Thread(target=fill, daemon=True).start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    register_host_reader(dst, factory)
    return {"Out": jnp.zeros((1,), jnp.float32)}


@register_op("open_files", differentiable=False, host_effect=True)
def open_files(ctx):
    """reference reader/open_files_op.cc: multi-file reader -- records
    from each recordio file in `file_names` streamed in order (the
    reference's thread pool becomes the double-buffer decorator when
    overlap is wanted)."""
    files = list(ctx.attr("file_names", []))
    dst = ctx.op.output("Out")[0]
    parser = _resolve_py_func(ctx.attr("parser_id", None), "open_files",
                              required=False)

    def factory():
        for fn in files:
            yield from _scan_recordio(fn, parser)

    register_host_reader(dst, factory)
    return {"Out": jnp.zeros((1,), jnp.float32)}
