"""Optimizer update ops -- optimizers are *graph ops* like the reference
(reference: paddle/fluid/operators/optimizers/sgd_op.cc, momentum_op.cc,
adam_op.cc, adagrad_op.cc, adamax_op.cc, adadelta_op.cc, rmsprop_op.cc,
ftrl_op.cc, decayed_adagrad_op.cc, lars_momentum_op.cc).

Each op consumes Param (+accumulators) and emits ParamOut (+accumulator
outs) that the Executor threads back into the scope with donated buffers:
a true in-place HBM update once XLA aliases the donated input. The
`inplace` metadata mirrors the reference's inplace_op_inference.h hints.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("sgd", differentiable=False,
             inplace={"ParamOut": "Param"})
def sgd(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    lr = ctx.input("LearningRate").reshape(())
    return {"ParamOut": p - lr * g}


@register_op("momentum", differentiable=False,
             inplace={"ParamOut": "Param", "VelocityOut": "Velocity"})
def momentum(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    v = ctx.input("Velocity")
    lr = ctx.input("LearningRate").reshape(())
    mu = ctx.attr("mu")
    v_out = mu * v + g
    if ctx.attr("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": p_out, "VelocityOut": v_out}


@register_op("dgc_momentum", differentiable=False,
             inplace={"ParamOut": "Param", "UOut": "U", "VOut": "V"})
def dgc_momentum(ctx):
    """Deep Gradient Compression momentum (reference optimizer.py:589 +
    details/all_reduce_op_handle.cc:65-227 sparse allreduce). The
    per-worker math lives in parallel/dgc.py dgc_momentum_step; under a
    GSPMD data-parallel program the incoming Grad is already the global
    mean, so the compression here governs *update* sparsity; the
    explicit compressed-wire collective form is
    parallel.dgc.dgc_allreduce_step for shard_map programs."""
    from ..parallel.dgc import dgc_momentum_step

    p, g = ctx.input("Param"), ctx.input("Grad")
    u, v = ctx.input("U"), ctx.input("V")
    step = ctx.input("CurrentStep").reshape(()).astype(jnp.int32)
    lr = ctx.input("LearningRate").reshape(())
    p_out, u_out, v_out = dgc_momentum_step(
        p, g, u, v, lr,
        mu=ctx.attr("mu"),
        step=step,
        sparsity=list(ctx.attr("sparsity", [0.999])),
        rampup_begin_step=ctx.attr("rampup_begin_step", 0),
        rampup_step=ctx.attr("rampup_step", 1),
        use_nesterov=ctx.attr("use_nesterov", False))
    # CurrentStep is advanced ONCE per step by the optimizer's
    # _finish_update increment op, not per-param here
    return {"ParamOut": p_out, "UOut": u_out, "VOut": v_out}


@register_op("dgc", differentiable=False,
             inplace={"U_out": "U", "V_out": "V"})
def dgc(ctx):
    """DGC gradient encode (reference operators/dgc_op.cc:23 DGCOp +
    dgc_op.h:38 DGCOpKernel; wired by optimizer.py:813 _dgc_op).
    Delegates to parallel/dgc.py dgc_encode; see its docstring for the
    TPU-native dense-masked EncodeGrad format."""
    from ..parallel.dgc import dgc_encode

    u, v, g = ctx.input("U"), ctx.input("V"), ctx.input("Grad")
    step = ctx.input("current_step").reshape(()).astype(jnp.int32)
    u_out, v_out, encode, grad_out, k = dgc_encode(
        u, v, g,
        m=ctx.attr("m", 0.9),
        step=step,
        sparsity=list(ctx.attr("sparsity", [0.999])),
        rampup_begin_step=int(ctx.attr("rampup_begin_step", 0.0)),
        rampup_step=int(ctx.attr("rampup_step", 1.0)),
        use_nesterov=ctx.attr("use_nesterov", True))
    return {"U_out": u_out, "V_out": v_out, "EncodeGrad": encode,
            "Grad_out": grad_out, "k": k}


@register_op("lars_momentum", differentiable=False,
             inplace={"ParamOut": "Param", "VelocityOut": "Velocity"})
def lars_momentum(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    v = ctx.input("Velocity")
    lr = ctx.input("LearningRate").reshape(())
    mu = ctx.attr("mu")
    lars_coeff = ctx.attr("lars_coeff", 0.001)
    lars_wd = ctx.attr("lars_weight_decay", 0.0005)
    p_norm = jnp.sqrt(jnp.sum(p * p))
    g_norm = jnp.sqrt(jnp.sum(g * g))
    local_lr = lr * lars_coeff * p_norm / (
        g_norm + lars_wd * p_norm + 1e-9)
    v_out = mu * v + local_lr * (g + lars_wd * p)
    return {"ParamOut": p - v_out, "VelocityOut": v_out}


@register_op("adam", differentiable=False,
             inplace={"ParamOut": "Param", "Moment1Out": "Moment1",
                      "Moment2Out": "Moment2"})
def adam(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m1, m2 = ctx.input("Moment1"), ctx.input("Moment2")
    b1p = ctx.input("Beta1Pow").reshape(())
    b2p = ctx.input("Beta2Pow").reshape(())
    lr = ctx.input("LearningRate").reshape(())
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_out = p - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
    out = {"ParamOut": p_out, "Moment1Out": m1_out, "Moment2Out": m2_out}
    if "Beta1PowOut" in ctx.op.outputs:
        out["Beta1PowOut"] = b1p.reshape(1) * b1
        out["Beta2PowOut"] = b2p.reshape(1) * b2
    return out


@register_op("adamax", differentiable=False,
             inplace={"ParamOut": "Param", "MomentOut": "Moment",
                      "InfNormOut": "InfNorm"})
def adamax(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m = ctx.input("Moment")
    inf = ctx.input("InfNorm")
    b1p = ctx.input("Beta1Pow").reshape(())
    lr = ctx.input("LearningRate").reshape(())
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf, jnp.abs(g) + eps)
    p_out = p - (lr / (1 - b1p)) * (m_out / inf_out)
    return {"ParamOut": p_out, "MomentOut": m_out, "InfNormOut": inf_out}


@register_op("adagrad", differentiable=False,
             inplace={"ParamOut": "Param", "MomentOut": "Moment"})
def adagrad(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(())
    eps = ctx.attr("epsilon", 1e-6)
    m_out = m + g * g
    p_out = p - lr * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("decayed_adagrad", differentiable=False,
             inplace={"ParamOut": "Param", "MomentOut": "Moment"})
def decayed_adagrad(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(())
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    m_out = decay * m + (1 - decay) * g * g
    p_out = p - lr * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("adadelta", differentiable=False,
             inplace={"ParamOut": "Param", "AvgSquaredGradOut":
                      "AvgSquaredGrad", "AvgSquaredUpdateOut":
                      "AvgSquaredUpdate"})
def adadelta(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    asg = ctx.input("AvgSquaredGrad")
    asu = ctx.input("AvgSquaredUpdate")
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    asg_out = rho * asg + (1 - rho) * g * g
    update = -jnp.sqrt((asu + eps) / (asg_out + eps)) * g
    asu_out = rho * asu + (1 - rho) * update * update
    return {"ParamOut": p + update, "AvgSquaredGradOut": asg_out,
            "AvgSquaredUpdateOut": asu_out}


@register_op("rmsprop", differentiable=False,
             inplace={"ParamOut": "Param", "MomentOut": "Moment",
                      "MeanSquareOut": "MeanSquare"})
def rmsprop(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    ms = ctx.input("MeanSquare")
    mom = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(())
    rho = ctx.attr("decay", 0.9)
    eps = ctx.attr("epsilon", 1e-10)
    momentum = ctx.attr("momentum", 0.0)
    centered = ctx.attr("centered", False)
    ms_out = rho * ms + (1 - rho) * g * g
    out = {"MeanSquareOut": ms_out}
    if centered:
        mg = ctx.input("MeanGrad")
        mg_out = rho * mg + (1 - rho) * g
        denom = ms_out - mg_out * mg_out + eps
        out["MeanGradOut"] = mg_out
    else:
        denom = ms_out + eps
    mom_out = momentum * mom + lr * g / jnp.sqrt(denom)
    out["MomentOut"] = mom_out
    out["ParamOut"] = p - mom_out
    return out


@register_op("ftrl", differentiable=False,
             inplace={"ParamOut": "Param", "SquaredAccumOut":
                      "SquaredAccumulator", "LinearAccumOut":
                      "LinearAccumulator"})
def ftrl(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    sq = ctx.input("SquaredAccumulator")
    lin = ctx.input("LinearAccumulator")
    lr = ctx.input("LearningRate").reshape(())
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    new_sq = sq + g * g
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (new_sq ** -lr_power - sq ** -lr_power) / lr
    lin_out = lin + g - sigma * p
    if lr_power == -0.5:
        x = l2 + jnp.sqrt(new_sq) / lr
    else:
        x = l2 + new_sq ** -lr_power / lr
    pre_shrink = (jnp.sign(lin_out) * l1 - lin_out) / x
    p_out = jnp.where(jnp.abs(lin_out) > l1, pre_shrink,
                      jnp.zeros_like(p))
    return {"ParamOut": p_out, "SquaredAccumOut": new_sq,
            "LinearAccumOut": lin_out}


@register_op("dpsgd", differentiable=False, needs_rng=True,
             inplace={"ParamOut": "Param"})
def dpsgd(ctx):
    """Differentially-private SGD (reference optimizers/dpsgd_op.cc era):
    clip per-batch grad + add gaussian noise."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    lr = ctx.input("LearningRate").reshape(())
    clip = ctx.attr("clip", 10.0)
    sigma = ctx.attr("sigma", 1.0)
    norm = jnp.sqrt(jnp.sum(g * g))
    g = g * jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    noise = sigma * clip * jax.random.normal(ctx.rng(), g.shape, g.dtype)
    return {"ParamOut": p - lr * (g + noise)}
