"""Distributed graph ops: send/recv/barriers/split_byref/prefetch.

Parity: reference paddle/fluid/operators/distributed_ops/ (send_op.cc,
recv_op.cc, send_barrier_op.cc, fetch_barrier_op.cc, prefetch_op.cc,
listen_and_serv_op.cc) + split_byref_op.cc.

TPU-native: these are *host* effects reached from inside the compiled
XLA program via `jax.experimental.io_callback(ordered=True)` — the XLA
analogue of the reference's RPC client calls made from graph ops. The
endpoint table they talk to is transpiler/pserver_runtime.py. ordered=
True pins the send -> barrier -> recv sequence exactly like the
reference's per-op program order.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

from ..core.registry import register_op
from ..core.types import to_jnp_dtype


def _endpoint(ep: str):
    from ..transpiler.pserver_runtime import get_endpoint

    return get_endpoint(ep)


@register_op("send", differentiable=False, host_effect=True)
def send(ctx):
    """Push grads (or init values) to endpoints; attrs: epmap aligned
    with X, varnames = remote names, init (startup push vs grad push)."""
    vals = ctx.inputs("X")
    epmap = ctx.attr("epmap")
    names = ctx.attr("varnames")
    is_init = ctx.attr("init", False)

    def _do(*arrays):
        for arr, ep, name in zip(arrays, epmap, names):
            rt = _endpoint(ep)
            if is_init:
                rt.push_init(name, arr)
            else:
                rt.push_grad(name, arr)
        return np.int32(0)

    io_callback(_do, jax.ShapeDtypeStruct((), jnp.int32), *vals,
                ordered=True)
    return {}


@register_op("send_barrier", differentiable=False, host_effect=True)
def send_barrier(ctx):
    endpoints = ctx.attr("endpoints")

    def _do():
        for ep in endpoints:
            _endpoint(ep).barrier()
        return np.int32(0)

    io_callback(_do, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return {}


@register_op("recv", differentiable=False, host_effect=True)
def recv(ctx):
    """Pull param blocks; attrs: epmap aligned with Out slot vars,
    varnames = remote names."""
    epmap = ctx.attr("epmap")
    names = ctx.attr("varnames")
    out_names = ctx.op.output("Out")
    block = ctx.op.block
    specs = []
    for n in out_names:
        var = block.var(n)
        specs.append(jax.ShapeDtypeStruct(
            tuple(var.shape), to_jnp_dtype(var.dtype or "float32")))

    def _do():
        outs = []
        for ep, name, spec in zip(epmap, names, specs):
            v = np.asarray(_endpoint(ep).pull(name))
            outs.append(v.astype(spec.dtype).reshape(spec.shape))
        return tuple(outs)

    vals = io_callback(_do, tuple(specs), ordered=True)
    return {"Out": list(vals)}


@register_op("fetch_barrier", differentiable=False, host_effect=True)
def fetch_barrier(ctx):
    def _do():
        return np.int32(0)

    io_callback(_do, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return {}


@register_op("split_byref", differentiable=False)
def split_byref(ctx):
    """Split X along dim0 into given sections (reference
    split_byref_op.cc; feeds per-endpoint send)."""
    x = ctx.input("X")
    sections = ctx.attr("sections")
    outs = []
    off = 0
    for s in sections:
        outs.append(jax.lax.slice_in_dim(x, off, off + s, axis=0))
        off += s
    return {"Out": outs}


def _prefetch_grad_maker(op, no_grad_set=frozenset()):
    """Sparse backward for the distributed lookup table (reference
    distribute_transpiler.py:1301 _split_table_grad_and_add_send_vars:
    grads are split by row ownership and sent to the owning pserver;
    here the grad op pushes (ids, rows) straight to the endpoints)."""
    from ..core.program import Operator, grad_var_name

    inputs = {"Ids": list(op.input("Ids")),
              "Out@GRAD": [grad_var_name(op.output("Out")[0])]}
    return [Operator(op.block, "prefetch_grad", inputs, {},
                     dict(op.attrs))]


@register_op("prefetch_grad", differentiable=False, host_effect=True)
def prefetch_grad(ctx):
    ids = ctx.input("Ids")
    dout = ctx.input("Out@GRAD")
    epmap = ctx.attr("epmap")
    names = ctx.attr("varnames")
    emb_dim = ctx.attr("emb_dim")
    lr_name = ctx.attr("lr_name", "")
    padding_idx = ctx.attr("padding_idx", -1)
    n_shards = len(epmap)
    flat_ids = jnp.reshape(ids, (-1,)).astype(jnp.int32)
    flat_g = jnp.reshape(dout, (-1, int(emb_dim)))

    def _do(idv, gv):
        idv = np.asarray(idv)
        gv = np.asarray(gv)
        if padding_idx >= 0:  # pad positions contribute no gradient
            keep = idv != padding_idx
            idv, gv = idv[keep], gv[keep]
        for shard, (ep, name) in enumerate(zip(epmap, names)):
            mask = (idv % n_shards) == shard
            if not mask.any():
                continue
            _endpoint(ep).push_sparse_grad(
                name, idv[mask] // n_shards, gv[mask], lr_name)
        return np.int32(0)

    io_callback(_do, jax.ShapeDtypeStruct((), jnp.int32), flat_ids,
                flat_g, ordered=True)
    return {}


@register_op("prefetch", grad_maker=_prefetch_grad_maker,
             stop_gradient_slots=("Ids",), host_effect=True)
def prefetch(ctx):
    """Distributed-lookup-table row fetch (reference prefetch_op.cc +
    parameter_prefetch.cc): gather rows of a row-sharded table from the
    endpoints that own them. Rows are mod-sharded across endpoints
    (ps_dispatcher round-robin row placement)."""
    ids = ctx.input("Ids")
    epmap = ctx.attr("epmap")
    names = ctx.attr("varnames")
    emb_dim = ctx.attr("emb_dim")
    padding_idx = ctx.attr("padding_idx", -1)
    n_shards = len(epmap)
    flat = jnp.reshape(ids, (-1,)).astype(jnp.int32)
    spec = jax.ShapeDtypeStruct((int(flat.shape[0]), int(emb_dim)),
                                jnp.float32)

    def _do(idv):
        idv = np.asarray(idv)
        out = np.zeros((idv.shape[0], emb_dim), np.float32)
        for shard, (ep, name) in enumerate(zip(epmap, names)):
            mask = (idv % n_shards) == shard
            if not mask.any():
                continue
            table = np.asarray(_endpoint(ep).pull(name))
            out[mask] = table[idv[mask] // n_shards]
        return out

    rows = io_callback(_do, spec, flat, ordered=True)
    if padding_idx >= 0:  # pad ids embed to zeros (lookup_table parity)
        rows = jnp.where((flat == padding_idx)[:, None],
                         jnp.zeros_like(rows), rows)
    out_shape = tuple(ids.shape) + (int(emb_dim),)
    if ids.ndim and ids.shape[-1] == 1:
        out_shape = tuple(ids.shape[:-1]) + (int(emb_dim),)
    return {"Out": jnp.reshape(rows, out_shape)}


@register_op("listen_and_serv", differentiable=False, host_effect=True)
def listen_and_serv(ctx):
    raise RuntimeError(
        "listen_and_serv is a host server loop, not a compiled op; run "
        "the pserver program via transpiler.pserver_runtime."
        "configure_endpoint(...) (the reference equivalent is "
        "listen_and_serv_op.cc RunImpl blocking the process)")


@register_op("allreduce", differentiable=False, host_effect=True)
def allreduce(ctx):
    """Cross-process allreduce (reference distributed_ops/
    allreduce_op.cc: in-graph ncclAllReduce for nccl2/collective
    mode). Single process: identity. Multi-process (jax.distributed
    initialized): the reduction crosses processes through the host
    bridge — process_allgather rides Gloo on CPU / ICI-DCN on TPU —
    then averages when reduce_type is mean."""
    x = ctx.input("X")
    reduce_type = ctx.attr("reduce_type", "sum")
    n_proc = jax.process_count()
    if n_proc == 1:
        return {"Out": x}
    spec = jax.ShapeDtypeStruct(x.shape, x.dtype)

    def _do(v):
        from jax.experimental import multihost_utils

        gathered = np.asarray(
            multihost_utils.process_allgather(jnp.asarray(v)))
        reducers = {"mean": gathered.mean, "sum": gathered.sum,
                    "max": gathered.max, "min": gathered.min,
                    "prod": gathered.prod}
        if reduce_type not in reducers:
            raise ValueError(
                f"allreduce: unsupported reduce_type {reduce_type!r}")
        return reducers[reduce_type](axis=0).astype(v.dtype)

    out = io_callback(_do, spec, x, ordered=True)
    return {"Out": out}


@register_op("checkpoint_notify", differentiable=False, host_effect=True)
def checkpoint_notify(ctx):
    """reference distributed_ops/checkpoint_notify_op.cc: tell every
    pserver in epmap to run its checkpoint save block (persist its
    shard of the distributed lookup table under `dir`). Host bridge:
    ordered io_callback -> PServerRuntime.save_checkpoint, the same
    transport every other pserver op here uses."""
    epmap = list(ctx.attr("epmap", []))
    dirname = ctx.attr("dir")
    table = ctx.attr("lookup_table", "")

    def _do():
        import os

        sub = os.path.join(dirname, "__lookup_table__") if table \
            else dirname
        for ep in epmap:
            _endpoint(ep).save_checkpoint(sub, prefix=table)
        return np.zeros((), np.int32)

    io_callback(_do, jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    return None


@register_op("gen_nccl_id", differentiable=False)
def gen_nccl_id(ctx):
    """reference distributed_ops/gen_nccl_id_op.cc: rank 0 generates an
    ncclUniqueId and broadcasts it over raw RPC so every trainer can
    join the NCCL ring. On TPU the coordination service started by
    jax.distributed.initialize (parallel/env.py) IS this bootstrap --
    the op validates the env is up and writes a placeholder id so
    reference startup programs run unchanged."""
    trainers = ctx.attr("trainers", [])
    if len(trainers) > 1 and jax.process_count() == 1:
        raise RuntimeError(
            "gen_nccl_id: multi-trainer program but jax.distributed is "
            "not initialized -- call "
            "paddle_tpu.parallel.init_distributed_env() first (the "
            "coordination-service replacement for the NCCL-id exchange)")
    return {"NCCLID": jnp.zeros((1,), jnp.int32)}


@register_op("ncclInit", differentiable=False)
def nccl_init(ctx):
    """reference nccl/nccl_op.cc ncclInit: build communicators for a
    device list. XLA collectives need no runtime communicator objects
    (compiler-scheduled over ICI); parity marker writing a placeholder
    Communicator."""
    return {"Communicator": jnp.zeros((1,), jnp.int32)}
