"""LoD rank-table machinery + projection/step RNNs.

Parity targets (reference paddle/fluid/operators/): lod_rank_table_op.cc,
max_sequence_len_op.cc, lod_tensor_to_array_op.cc,
array_to_lod_tensor_op.cc, shrink_rnn_memory_op.cc,
reorder_lod_tensor_by_rank_op.cc, lstmp_op.cc, recurrent_op.cc.

LoD design recap (layers/sequence.py): variable-length batches are
padded [B, T, ...] with an int32 ``@SEQ_LEN`` companion of per-row
lengths -- XLA needs static shapes, so the reference's LoD offsets
become lengths and "shrinking" becomes masking. The rank table is the
same (index, length) descending sort the reference builds; the tensor
array carries FULL-batch per-timestep slices in rank order (no batch
shrink -- finished rows are masked by consumers instead, which is the
numerics-preserving static-shape form of the same computation).

``recurrent`` runs a traced sub-block under lax.scan -- the
StaticRNN backend (reference recurrent_op.cc re-executes the block per
step through an inner executor; here the block is traced ONCE and the
time loop is a compiled scan).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op, run_op
from .control_flow_ops import TensorArray, _no_infer


@register_op("lod_rank_table", differentiable=False,
             infer_shape=_no_infer)
def lod_rank_table(ctx):
    """reference lod_rank_table_op.cc: (index, length) rows sorted by
    length descending (stable)."""
    x = ctx.input("X")
    seq_len = ctx.input("SeqLen")
    if seq_len is None:
        b, t = x.shape[0], x.shape[1]
        seq_len = jnp.full((b,), t, jnp.int32)
    order = jnp.argsort(-seq_len.astype(jnp.int32), stable=True)
    return {"Out": jnp.stack(
        [order.astype(jnp.int32),
         seq_len[order].astype(jnp.int32)], axis=1)}


@register_op("max_sequence_len", differentiable=False,
             infer_shape=_no_infer)
def max_sequence_len(ctx):
    """reference max_sequence_len_op.cc: longest length in the rank
    table (row 0 after the descending sort)."""
    table = ctx.input("RankTable")
    return {"Out": table[0, 1].astype(jnp.int64).reshape(1)}


@register_op("lod_tensor_to_array", differentiable=False,
             infer_shape=_no_infer)
def lod_tensor_to_array(ctx):
    """reference lod_tensor_to_array_op.cc: split [B, T, ...] into a
    T-entry tensor array of per-timestep batches in rank order."""
    x = ctx.input("X")
    table = ctx.input("RankTable")
    order = table[:, 0]
    xr = x[order]  # rank order
    arr = TensorArray(jnp.swapaxes(xr, 0, 1)[t] for t in range(x.shape[1]))
    return {"Out": [arr]}


@register_op("array_to_lod_tensor", differentiable=False,
             infer_shape=_no_infer)
def array_to_lod_tensor(ctx):
    """reference array_to_lod_tensor_op.cc: inverse of
    lod_tensor_to_array -- stack the array and undo the rank permute."""
    arr = ctx.input("X")
    table = ctx.input("RankTable")
    order = table[:, 0]
    stacked = jnp.stack(list(arr), axis=1)  # [B, T, ...] rank order
    inv = jnp.zeros_like(order).at[order].set(
        jnp.arange(order.shape[0], dtype=order.dtype))
    return {"Out": stacked[inv]}


@register_op("reorder_lod_tensor_by_rank", differentiable=False,
             infer_shape=_no_infer)
def reorder_lod_tensor_by_rank(ctx):
    """reference reorder_lod_tensor_by_rank_op.cc."""
    x = ctx.input("X")
    table = ctx.input("RankTable")
    return {"Out": x[table[:, 0]]}


@register_op("shrink_rnn_memory", infer_shape=_no_infer)
def shrink_rnn_memory(ctx):
    """reference shrink_rnn_memory_op.cc keeps only the rows whose
    sequence is still active at step I. Static-shape form: full batch
    out, with finished rows HELD at their input value by the consumer's
    masking; the active count rides along so user code can still read
    it (rows are rank-ordered, so active rows are a prefix)."""
    x = ctx.input("X")
    table = ctx.input("RankTable")
    i = ctx.input("I")
    step = jnp.reshape(i, ()).astype(jnp.int32)
    active = jnp.sum((table[:, 1] > step).astype(jnp.int32))
    return {"Out": x, "ActiveCount": active.reshape(1)}


@register_op("ifelse", infer_shape=_no_infer,
             stop_gradient_slots=("Cond",))
def ifelse(ctx):
    """reference layers/control_flow.py:1126 IfElse (split_lod_tensor /
    merge_lod_tensor ops): rows where Cond is true flow through the
    true block, the rest through the false block, outputs merged back
    in row order. Static-shape form: BOTH branches run on the full
    batch (row-independent math, same values the reference computes on
    its split halves) and a row-wise where() does the merge -- no
    dynamic shapes, branches fuse into one XLA program."""
    tb = ctx.attr("true_block")
    fb = ctx.attr("false_block")
    t_outs = list(ctx.attr("true_outs"))
    f_outs = list(ctx.attr("false_outs"))
    externals = list(ctx.attr("externals"))
    cond = ctx.input("Cond")
    exs = dict(zip(externals, ctx.inputs("X")))

    def run_branch(blk, names):
        env = dict(exs)
        for op in blk.ops:
            run_op(op, env, rng_cell=None, rng_salt=op._uid)
        return [env[n] for n in names]

    tv = run_branch(tb, t_outs)
    fv = run_branch(fb, f_outs)
    merged = []
    for a, b in zip(tv, fv):
        c = cond.reshape((-1,) + (1,) * (a.ndim - 1)).astype(bool)
        merged.append(jnp.where(c, a, b))
    return {"Out": merged}


# --------------------------------------------------------------------------
# lstmp: LSTM with a recurrent projection layer
# --------------------------------------------------------------------------
_ACTS = {
    "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
    "relu": jax.nn.relu, "identity": lambda v: v,
}


@register_op("lstmp", stop_gradient_slots=("SeqLen",))
def lstmp(ctx):
    """reference lstmp_op.cc: LSTM whose recurrence runs on a learned
    projection r_t = act_proj(h_t @ W_proj) (Sak et al. 2014). Input
    [B,T,4H] pre-projected, Weight [P,4H], ProjWeight [H,P], Bias
    [1,4H(+3H peepholes)]; gate order i,f,c,o."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    w_proj = ctx.input("ProjWeight")
    bias = ctx.input("Bias")
    seq_len = ctx.input("SeqLen")
    use_peepholes = ctx.attr("use_peepholes", True)
    is_reverse = ctx.attr("is_reverse", False)
    act_gate = _ACTS[ctx.attr("gate_activation", "sigmoid")]
    act_cell = _ACTS[ctx.attr("cell_activation", "tanh")]
    act_cand = _ACTS[ctx.attr("candidate_activation", "tanh")]
    act_proj = _ACTS[ctx.attr("proj_activation", "tanh")]
    b_sz, t, four_h = x.shape
    h_dim = four_h // 4
    p_dim = w_proj.shape[1]
    if bias is not None:
        x = x + bias[..., :4 * h_dim].reshape(1, 1, 4 * h_dim)
        if use_peepholes:
            peep = bias[..., 4 * h_dim:].reshape(3 * h_dim)
            w_ic, w_fc, w_oc = (peep[:h_dim], peep[h_dim:2 * h_dim],
                                peep[2 * h_dim:])
        else:
            w_ic = w_fc = w_oc = None
    else:
        w_ic = w_fc = w_oc = None
    if seq_len is None:
        seq_len = jnp.full((b_sz,), t, dtype=jnp.int32)
    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    r_init = (act_proj(h0 @ w_proj) if h0 is not None
              else jnp.zeros((b_sz, p_dim), x.dtype))
    c_init = c0 if c0 is not None else jnp.zeros((b_sz, h_dim), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)
    steps = jnp.arange(t)
    if is_reverse:
        xs = xs[::-1]
        steps = steps[::-1]

    def cell(carry, inp):
        r_prev, c_prev = carry
        xt, step = inp
        gates = xt + r_prev @ w
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i = act_gate(gi)
        f = act_gate(gf)
        c = f * c_prev + i * act_cand(gc)
        if w_oc is not None:
            go = go + c * w_oc
        o = act_gate(go)
        h = o * act_cell(c)
        r = act_proj(h @ w_proj)
        valid = (step < seq_len)[:, None].astype(x.dtype)
        r = valid * r + (1 - valid) * r_prev
        c = valid * c + (1 - valid) * c_prev
        return (r, c), (r, c)

    (_, _), (rs, cs) = lax.scan(cell, (r_init, c_init), (xs, steps))
    if is_reverse:
        rs, cs = rs[::-1], cs[::-1]
    return {"Projection": jnp.swapaxes(rs, 0, 1),
            "Cell": jnp.swapaxes(cs, 0, 1)}


# --------------------------------------------------------------------------
# recurrent: sub-block stepped over time (StaticRNN backend)
# --------------------------------------------------------------------------
@register_op("recurrent", infer_shape=_no_infer,
             stop_gradient_slots=("SeqLen",))
def recurrent(ctx):
    """reference recurrent_op.cc runs its sub-block once per step via an
    inner executor, linking `memories` across steps. Here the block is
    traced once and stepped by lax.scan (compiled time loop).

    Layout follows the reference StaticRNN: sequence inputs are
    TIME-MAJOR [T, ...] and sliced to [...] per step; stacked outputs
    are [T, ...].

    inputs: X = sequence inputs, Init = initial memory values, Ex =
    read-only externals, SeqLen (optional [B] lengths; batch is then
    dim 0 of each slice). attrs: sub_block; x_names (in-block names of
    the per-step slices); pre_names/mem_names (memory in/out names in
    the block); out_names (per-step outputs to stack); externals;
    reverse; mask_memories (DynamicRNN semantics: finished rows hold
    their memory and emit zeros). outputs: Out = stacked per out_name;
    MemFinal = final memory values.
    """
    sub = ctx.attr("sub_block")
    x_names = list(ctx.attr("x_names", []))
    pre_names = list(ctx.attr("pre_names", []))
    mem_names = list(ctx.attr("mem_names", []))
    out_names = list(ctx.attr("out_names", []))
    externals = list(ctx.attr("externals", []))
    reverse = ctx.attr("reverse", False)
    mask_memories = ctx.attr("mask_memories", False)
    batch_major = ctx.attr("batch_major", False)
    xs = ctx.inputs("X")
    inits = ctx.inputs("Init")
    seq_len = ctx.input("SeqLen")
    exs = dict(zip(externals, ctx.inputs("Ex")))
    if batch_major:  # DynamicRNN convention: [B, T, ...] outer layout
        xs = [jnp.swapaxes(x, 0, 1) for x in xs]
    t = xs[0].shape[0] if xs else ctx.attr("seq_len")

    seq = list(xs)
    steps = jnp.arange(t)
    if reverse:
        seq = [s[::-1] for s in seq]
        steps = steps[::-1]

    def step(carry, scanned):
        slices, tstep = scanned
        env = dict(exs)
        env.update(zip(x_names, slices))
        env.update(zip(pre_names, carry))
        for op in sub.ops:
            run_op(op, env, rng_cell=None, rng_salt=op._uid)
        new_carry = tuple(env[n] for n in mem_names)
        outs = tuple(env[n] for n in out_names)
        if mask_memories and seq_len is not None:
            def _mask(new, old):
                valid = (tstep < seq_len).reshape(
                    (-1,) + (1,) * (new.ndim - 1))
                return jnp.where(valid, new, old)

            new_carry = tuple(_mask(n, o)
                              for n, o in zip(new_carry, carry))
            outs = tuple(_mask(o, jnp.zeros_like(o)) for o in outs)
        return new_carry, outs

    final_mem, stacked = lax.scan(step, tuple(inits),
                                  (tuple(seq), steps), length=t)
    outs = list(stacked)
    if reverse:
        outs = [o[::-1] for o in outs]
    if batch_major:
        outs = [jnp.swapaxes(o, 0, 1) for o in outs]
    return {"Out": outs, "MemFinal": list(final_mem)}
