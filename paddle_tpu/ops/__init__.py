"""Op library: importing this package registers all kernels."""
from . import tensor_ops  # noqa: F401
from . import math_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import decode_ops  # noqa: F401
from . import loss_extra_ops  # noqa: F401
from . import dist_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import host_ops  # noqa: F401
from . import extra_ops  # noqa: F401
from . import extra_ops2  # noqa: F401
from . import lod_ops  # noqa: F401
