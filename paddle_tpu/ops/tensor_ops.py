"""Tensor creation / manipulation ops.

Parity targets: reference paddle/fluid/operators/fill_constant_op.cc,
gaussian_random_op.cc, uniform_random_op.cc, reshape_op.cc, transpose_op.cc,
concat_op.cc, split_op.cc, cast_op.cc, assign_op.cc, scale_op.cc, sum_op.cc,
stack_op.cc, gather_op.cc, slice_op.cc, expand_op.cc, squeeze/unsqueeze,
shape_op.cc, one_hot_op.cc, range_op.cc, top_k_op.cc, arg_max/min.
Each is a pure jnp computation; XLA fuses them -- no hand-written kernels
needed at this tier (Pallas is reserved for the genuinely hot paths).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from ..core.types import to_jnp_dtype


@register_op("fill_constant", differentiable=False)
def fill_constant(ctx):
    shape = [int(s) for s in ctx.attr("shape", [1])]
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    value = ctx.attr("value", 0.0)
    return jnp.full(shape, value, dtype=dtype)


@register_op("fill_any_like", differentiable=False)
def fill_any_like(ctx):
    x = ctx.input("X")
    return jnp.full_like(x, ctx.attr("value", 0.0))


@register_op("fill_zeros_like", differentiable=False)
def fill_zeros_like(ctx):
    return jnp.zeros_like(ctx.input("X"))


@register_op("gaussian_random", differentiable=False, needs_rng=True)
def gaussian_random(ctx):
    shape = [int(s) for s in ctx.attr("shape")]
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    mean = ctx.attr("mean", 0.0)
    std = ctx.attr("std", 1.0)
    key = _seeded_key(ctx)
    return (jax.random.normal(key, shape, dtype=jnp.float32) * std
            + mean).astype(dtype)


@register_op("uniform_random", differentiable=False, needs_rng=True)
def uniform_random(ctx):
    shape = [int(s) for s in ctx.attr("shape")]
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    lo = ctx.attr("min", -1.0)
    hi = ctx.attr("max", 1.0)
    key = _seeded_key(ctx)
    return jax.random.uniform(key, shape, dtype=jnp.float32,
                              minval=lo, maxval=hi).astype(dtype)


@register_op("truncated_gaussian_random", differentiable=False,
             needs_rng=True)
def truncated_gaussian_random(ctx):
    shape = [int(s) for s in ctx.attr("shape")]
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    mean = ctx.attr("mean", 0.0)
    std = ctx.attr("std", 1.0)
    key = _seeded_key(ctx)
    x = jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32)
    return (x * std + mean).astype(dtype)


def _seeded_key(ctx):
    s = ctx.attr("seed", 0)
    if s:
        return jax.random.PRNGKey(s)
    return ctx.rng()


@register_op("assign")
def assign(ctx):
    return ctx.input("X")


@register_op("shape", differentiable=False)
def shape_op(ctx):
    return jnp.asarray(ctx.input("Input").shape, dtype=jnp.int32)


@register_op("cast")
def cast(ctx):
    return ctx.input("X").astype(to_jnp_dtype(ctx.attr("out_dtype")))


@register_op("scale")
def scale(ctx):
    x = ctx.input("X")
    s = ctx.attr("scale", 1.0)
    b = ctx.attr("bias", 0.0)
    if ctx.attr("bias_after_scale", True):
        return x * s + b
    return (x + b) * s


def _reshape_kernel(ctx):
    x = ctx.input("X")
    shape = list(ctx.attr("shape"))
    # fluid semantics (reference reshape_op.cc): 0 -> copy input dim,
    # -1 -> inferred
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return jnp.reshape(x, shape)


register_op("reshape")(_reshape_kernel)


@register_op("reshape2")
def reshape2(ctx):
    out = _reshape_kernel(ctx)
    res = {"Out": out}
    if "XShape" in ctx.op.outputs:
        res["XShape"] = jnp.zeros((0,) + ctx.input("X").shape,
                                  dtype=jnp.float32)
    return res


@register_op("transpose")
def transpose(ctx):
    return jnp.transpose(ctx.input("X"), ctx.attr("axis"))


@register_op("transpose2")
def transpose2(ctx):
    res = {"Out": jnp.transpose(ctx.input("X"), ctx.attr("axis"))}
    if "XShape" in ctx.op.outputs:
        res["XShape"] = jnp.zeros((0,) + ctx.input("X").shape,
                                  dtype=jnp.float32)
    return res


@register_op("flatten")
def flatten(ctx):
    x = ctx.input("X")
    ax = ctx.attr("axis", 1)
    lead = int(np.prod(x.shape[:ax])) if ax > 0 else 1
    return jnp.reshape(x, (lead, -1))


@register_op("flatten2")
def flatten2(ctx):
    x = ctx.input("X")
    ax = ctx.attr("axis", 1)
    lead = int(np.prod(x.shape[:ax])) if ax > 0 else 1
    res = {"Out": jnp.reshape(x, (lead, -1))}
    if "XShape" in ctx.op.outputs:
        res["XShape"] = jnp.zeros((0,) + x.shape, dtype=jnp.float32)
    return res


@register_op("squeeze")
def squeeze(ctx):
    x = ctx.input("X")
    axes = ctx.attr("axes", [])
    if axes:
        return jnp.squeeze(x, axis=tuple(a for a in axes
                                         if x.shape[a] == 1))
    return jnp.squeeze(x)


@register_op("squeeze2")
def squeeze2(ctx):
    x = ctx.input("X")
    axes = ctx.attr("axes", [])
    if axes:
        out = jnp.squeeze(x, axis=tuple(a for a in axes if x.shape[a] == 1))
    else:
        out = jnp.squeeze(x)
    res = {"Out": out}
    if "XShape" in ctx.op.outputs:
        res["XShape"] = jnp.zeros((0,) + x.shape, dtype=jnp.float32)
    return res


@register_op("unsqueeze")
def unsqueeze(ctx):
    x = ctx.input("X")
    for a in sorted(ctx.attr("axes")):
        x = jnp.expand_dims(x, a)
    return x


@register_op("unsqueeze2")
def unsqueeze2(ctx):
    x = ctx.input("X")
    orig = x
    for a in sorted(ctx.attr("axes")):
        x = jnp.expand_dims(x, a)
    res = {"Out": x}
    if "XShape" in ctx.op.outputs:
        res["XShape"] = jnp.zeros((0,) + orig.shape, dtype=jnp.float32)
    return res


@register_op("concat")
def concat(ctx):
    xs = ctx.inputs("X")
    return jnp.concatenate(xs, axis=ctx.attr("axis", 0))


@register_op("split")
def split(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    num = ctx.attr("num", 0)
    sections = list(ctx.attr("sections", []))
    if num:
        return {"Out": list(jnp.split(x, num, axis=axis))}
    neg = [i for i, s in enumerate(sections) if s == -1]
    if len(neg) > 1:
        raise ValueError(
            f"split: more than one -1 entry in sections {sections}")
    if neg:
        # fluid allows ONE -1 section, inferred from the axis extent
        rest = int(x.shape[axis]) - sum(s for s in sections if s != -1)
        if rest < 0:
            # jnp.split would silently clamp the out-of-range index
            # into a zero-width slice; the native kernel names this
            # case too (xla_train.cc splitKernel)
            raise ValueError(
                f"split: explicit sections {sections} exceed the axis "
                f"extent {int(x.shape[axis])}; cannot infer the -1 "
                f"section")
        sections[neg[0]] = rest
    idx = np.cumsum(sections)[:-1]
    return {"Out": list(jnp.split(x, idx, axis=axis))}


@register_op("stack")
def stack(ctx):
    return jnp.stack(ctx.inputs("X"), axis=ctx.attr("axis", 0))


@register_op("unstack")
def unstack(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(s, axis=axis)
                  for s in jnp.split(x, n, axis=axis)]}


@register_op("sum")
def sum_op(ctx):
    xs = [x for x in ctx.inputs("X") if x is not None]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register_op("gather", stop_gradient_slots=("Index",))
def gather(ctx):
    return jnp.take(ctx.input("X"), ctx.input("Index").astype(jnp.int32),
                    axis=0)


@register_op("gather_nd", stop_gradient_slots=("Index",))
def gather_nd(ctx):
    x = ctx.input("X")
    idx = ctx.input("Index").astype(jnp.int32)
    return x[tuple(jnp.moveaxis(idx, -1, 0))]


@register_op("scatter", stop_gradient_slots=("Ids",))
def scatter(ctx):
    x = ctx.input("X")
    ids = ctx.input("Ids").astype(jnp.int32).reshape(-1)
    upd = ctx.input("Updates")
    if ctx.attr("overwrite", True):
        return x.at[ids].set(upd)
    return x.at[ids].add(upd)


@register_op("slice")
def slice_op(ctx):
    x = ctx.input("Input")
    axes = ctx.attr("axes")
    starts = ctx.attr("starts")
    ends = ctx.attr("ends")
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return x[tuple(idx)]


@register_op("strided_slice")
def strided_slice(ctx):
    x = ctx.input("Input")
    axes, starts = ctx.attr("axes"), ctx.attr("starts")
    ends, strides = ctx.attr("ends"), ctx.attr("strides")
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return x[tuple(idx)]


@register_op("expand")
def expand(ctx):
    x = ctx.input("X")
    times = ctx.attr("expand_times")
    return jnp.tile(x, times)


@register_op("expand_as")
def expand_as(ctx):
    x = ctx.input("X")
    target = ctx.input("target_tensor")
    times = [t // s for t, s in zip(target.shape, x.shape)]
    return jnp.tile(x, times)


@register_op("tile")
def tile(ctx):
    return jnp.tile(ctx.input("X"), ctx.attr("repeat_times"))


@register_op("pad")
def pad(ctx):
    x = ctx.input("X")
    paddings = ctx.attr("paddings")
    pw = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return jnp.pad(x, pw, constant_values=ctx.attr("pad_value", 0.0))


@register_op("pad2d")
def pad2d(ctx):
    x = ctx.input("X")
    p = ctx.attr("paddings")  # [top, bottom, left, right]
    mode = ctx.attr("mode", "constant")
    fmt = ctx.attr("data_format", "NCHW")
    if fmt == "NCHW":
        pw = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    else:
        pw = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    if mode == "constant":
        return jnp.pad(x, pw, constant_values=ctx.attr("pad_value", 0.0))
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return jnp.pad(x, pw, mode=jmode)


@register_op("crop")
def crop(ctx):
    x = ctx.input("X")
    offsets = ctx.attr("offsets")
    shape = ctx.attr("shape")
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return x[idx]


@register_op("one_hot", differentiable=False)
def one_hot(ctx):
    x = ctx.input("X").astype(jnp.int32)
    depth = ctx.attr("depth")
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x[..., 0]
    return jax.nn.one_hot(x, depth, dtype=jnp.float32)


@register_op("range", differentiable=False)
def range_op(ctx):
    # static-shape requirement: bounds must be attrs under jit (the
    # layers.range wrapper passes python scalars through); traced
    # Start/End/Step inputs only work with concrete host-side values.
    dtype = jnp.dtype(ctx.attr("dtype", "float32"))
    start = ctx.attr("start", None)
    if start is not None:
        bounds = (start, ctx.attr("end"), ctx.attr("step"))
    else:
        bounds = (ctx.input("Start"), ctx.input("End"),
                  ctx.input("Step"))
    # compute host-side in float64, then cast to the declared var dtype
    # (ADVICE r2: a float32 arange under an int-typed var breaks
    # while-loop carry dtypes, and float32 intermediates corrupt int
    # sequences past 2^24)
    vals = np.arange(*(float(b) for b in bounds))
    return jnp.asarray(vals.astype(dtype))


@register_op("top_k", differentiable=False)
def top_k(ctx):
    x = ctx.input("X")
    k = ctx.attr("k", 1)
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(jnp.int32)}


@register_op("arg_max", differentiable=False)
def arg_max(ctx):
    return jnp.argmax(ctx.input("X"),
                      axis=ctx.attr("axis", -1)).astype(jnp.int32)


@register_op("arg_min", differentiable=False)
def arg_min(ctx):
    return jnp.argmin(ctx.input("X"),
                      axis=ctx.attr("axis", -1)).astype(jnp.int32)


@register_op("argsort", differentiable=False)
def argsort(ctx):
    x = ctx.input("X")
    ax = ctx.attr("axis", -1)
    idx = jnp.argsort(x, axis=ax)
    return {"Out": jnp.sort(x, axis=ax), "Indices": idx.astype(jnp.int32)}


@register_op("where", stop_gradient_slots=("Condition",))
def where_op(ctx):
    return jnp.where(ctx.input("Condition"), ctx.input("X"),
                     ctx.input("Y"))


@register_op("uniform_random_batch_size_like", differentiable=False,
             needs_rng=True)
def uniform_random_batch_size_like(ctx):
    ref = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    bidx = ctx.attr("input_dim_idx", 0)
    oidx = ctx.attr("output_dim_idx", 0)
    shape[oidx] = ref.shape[bidx]
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    return jax.random.uniform(_seeded_key(ctx), shape, jnp.float32, lo, hi)


@register_op("fill_constant_batch_size_like", differentiable=False)
def fill_constant_batch_size_like(ctx):
    ref = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    bidx = ctx.attr("input_dim_idx", 0)
    oidx = ctx.attr("output_dim_idx", 0)
    shape[oidx] = ref.shape[bidx]
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    return jnp.full(shape, ctx.attr("value", 0.0), dtype=dtype)


@register_op("increment")
def increment(ctx):
    return ctx.input("X") + ctx.attr("step", 1.0)


@register_op("clip")
def clip(ctx):
    return jnp.clip(ctx.input("X"), ctx.attr("min"), ctx.attr("max"))


@register_op("clip_by_norm")
def clip_by_norm(ctx):
    x = ctx.input("X")
    max_norm = ctx.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(x * x))
    return jnp.where(norm > max_norm, x * (max_norm / norm), x)


@register_op("isfinite", differentiable=False)
def isfinite(ctx):
    xs = ctx.inputs("X")
    ok = jnp.array(True)
    for x in xs:
        ok = ok & jnp.all(jnp.isfinite(x))
    return ok


@register_op("reverse")
def reverse(ctx):
    x = ctx.input("X")
    for a in ctx.attr("axis"):
        x = jnp.flip(x, axis=a)
    return x


@register_op("assign_value", differentiable=False)
def assign_value(ctx):
    vals = np.asarray(ctx.attr("values"))
    dtype = to_jnp_dtype(ctx.attr("dtype", "float32"))
    shape = ctx.attr("shape", list(vals.shape))
    return jnp.asarray(vals, dtype=dtype).reshape(shape)


@register_op("gaussian_random_batch_size_like", differentiable=False,
             needs_rng=True)
def gaussian_random_batch_size_like(ctx):
    """reference operators/gaussian_random_batch_size_like_op.cc:
    normal samples with the batch dim copied from Input."""
    ref = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    bidx = ctx.attr("input_dim_idx", 0)
    oidx = ctx.attr("output_dim_idx", 0)
    shape[oidx] = ref.shape[bidx]
    mean = ctx.attr("mean", 0.0)
    std = ctx.attr("std", 1.0)
    return mean + std * jax.random.normal(_seeded_key(ctx), shape,
                                          jnp.float32)
