"""Quantization ops.

Parity: reference paddle/fluid/operators/fake_quantize_op.cc
(fake_quantize_abs_max, fake_quantize_range_abs_max,
fake_quantize_moving_average_abs_max, fake_channel_wise_quantize_abs_max),
fake_dequantize_op.cc, and the MKLDNN int8 quantize_op.cc/dequantize_op.cc
/requantize_op.cc.

TPU-first notes: fake-quant is simulated quantization — round to the
int grid but stay in float (XLA fuses the round into the surrounding
ops); the straight-through estimator (identity grad inside the clip
range) is registered as an explicit grad op, mirroring the reference's
FakeQuantGradFunctor. Real int8 quantize/dequantize produce int8
arrays (useful for weight storage; TPU MXU serving uses bf16 — see
inference.AnalysisConfig.enable_tpu_bf16).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.program import Operator, grad_var_name
from ..core.registry import register_op


def _ste_grad_maker(x_slot="X", out_slot="Out"):
    """Straight-through estimator: dX = dOut inside the quant range
    (zero where the forward clipped). The forward's OutScale is threaded
    into the grad op so the mask uses the ACTUAL scale (EMA/window
    scales can be below max|x|)."""

    def maker(op, no_grad_set=frozenset()):
        x_name = op.input(x_slot)[0]
        if x_name in no_grad_set:
            return []
        inputs = {x_slot: [x_name],
                  "OutScale": list(op.output("OutScale")),
                  "Out@GRAD": [grad_var_name(op.output(out_slot)[0])]}
        return [Operator(op.block, "fake_quant_ste_grad", inputs,
                         {"X@GRAD": [grad_var_name(x_name)]},
                         dict(op.attrs))]

    return maker


@register_op("fake_quant_ste_grad", differentiable=False)
def fake_quant_ste_grad(ctx):
    dy = ctx.input("Out@GRAD")
    x = ctx.input("X")
    scale = ctx.input("OutScale")
    if scale is None:
        scale = jnp.max(jnp.abs(x))
    else:
        scale = scale.reshape((-1,) + (1,) * (x.ndim - 1)) \
            if scale.size > 1 else scale.reshape(())
    mask = (jnp.abs(x) <= scale).astype(dy.dtype)
    return {"X@GRAD": dy * mask}


def _quantize(x, scale, bit_length):
    bnt = float((1 << (bit_length - 1)) - 1)
    s = jnp.maximum(scale, 1e-8)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * bnt) / bnt * s


@register_op("fake_quantize_abs_max", grad_maker=_ste_grad_maker())
def fake_quantize_abs_max(ctx):
    """reference fake_quantize_op.cc FakeQuantizeAbsMaxOp."""
    x = ctx.input("X")
    bits = ctx.attr("bit_length", 8)
    scale = jnp.max(jnp.abs(x))
    return {"Out": _quantize(x, scale, bits),
            "OutScale": scale.reshape(1)}


@register_op("fake_channel_wise_quantize_abs_max",
             grad_maker=_ste_grad_maker())
def fake_channel_wise_quantize_abs_max(ctx):
    """Per-output-channel scales (dim 0), reference
    FakeChannelWiseQuantizeAbsMaxOp."""
    x = ctx.input("X")
    bits = ctx.attr("bit_length", 8)
    axes = tuple(range(1, x.ndim))
    scale = jnp.max(jnp.abs(x), axis=axes)
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    return {"Out": _quantize(x, scale.reshape(shape), bits),
            "OutScale": scale}


@register_op("fake_quantize_range_abs_max", grad_maker=_ste_grad_maker())
def fake_quantize_range_abs_max(ctx):
    """reference FakeQuantizeRangeAbsMaxOp: scale = max over a sliding
    window of per-step abs-max (training); frozen scale at inference."""
    x = ctx.input("X")
    in_scale = ctx.input("InScale")
    bits = ctx.attr("bit_length", 8)
    window = ctx.attr("window_size", 10000)
    is_test = ctx.attr("is_test", False)
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = in_scale.reshape(())
        return {"Out": _quantize(x, scale, bits),
                "OutScale": in_scale}
    scale = jnp.maximum(cur, in_scale.reshape(()) *
                        (1.0 - 1.0 / float(window)))
    return {"Out": _quantize(x, scale, bits),
            "OutScale": scale.reshape(1)}


@register_op("fake_quantize_moving_average_abs_max",
             grad_maker=_ste_grad_maker())
def fake_quantize_moving_average_abs_max(ctx):
    """reference FakeQuantizeMovingAverageAbsMaxOp: EMA of abs-max."""
    x = ctx.input("X")
    in_scale = ctx.input("InScale")
    in_state = ctx.input("InState")
    in_accum = ctx.input("InAccum")
    rate = ctx.attr("moving_rate", 0.9)
    bits = ctx.attr("bit_length", 8)
    is_test = ctx.attr("is_test", False)
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = in_scale.reshape(())
        return {"Out": _quantize(x, scale, bits), "OutScale": in_scale,
                "OutState": in_state, "OutAccum": in_accum}
    state = (in_state.reshape(()) * rate + 1.0 if in_state is not None
             else jnp.asarray(1.0))
    accum = (in_accum.reshape(()) * rate + cur if in_accum is not None
             else cur)
    scale = accum / state
    out = {"Out": _quantize(x, scale, bits),
           "OutScale": scale.reshape(1)}
    if "OutState" in ctx.op.outputs:
        out["OutState"] = state.reshape(1)
        out["OutAccum"] = accum.reshape(1)
    return out


@register_op("fake_dequantize_max_abs", differentiable=False)
def fake_dequantize_max_abs(ctx):
    """reference fake_dequantize_op.cc: y = x * scale / max_range."""
    x = ctx.input("X")
    scale = ctx.input("Scale").reshape(())
    max_range = ctx.attr("max_range", 127.0)
    return {"Out": x.astype(jnp.float32) * scale / max_range}


@register_op("fake_channel_wise_dequantize_max_abs",
             differentiable=False)
def fake_channel_wise_dequantize_max_abs(ctx):
    x = ctx.input("X")
    scales = ctx.inputs("Scales")
    quant_bits = ctx.attr("quant_bits", [8])
    out = x.astype(jnp.float32)
    s0 = scales[0]
    bnt = float((1 << (int(quant_bits[0]) - 1)) - 1)
    shape = (out.shape[0],) + (1,) * (out.ndim - 1)
    out = out * s0.reshape(shape) / bnt
    if len(scales) > 1 and scales[1] is not None:
        bnt1 = float((1 << (int(quant_bits[1]) - 1)) - 1)
        out = out * scales[1].reshape(()) / bnt1
    return {"Out": out}


@register_op("quantize", differentiable=False)
def quantize(ctx):
    """Real int8 quantize (reference mkldnn quantize_op.cc)."""
    x = ctx.input("Input")
    scale = ctx.attr("Scale", 1.0)
    return {"Output": jnp.clip(jnp.round(x * scale), -128, 127)
            .astype(jnp.int8)}


@register_op("dequantize", differentiable=False)
def dequantize(ctx):
    x = ctx.input("Input")
    scale = ctx.attr("Scale", 1.0)
    return {"Output": x.astype(jnp.float32) / scale}


@register_op("requantize", differentiable=False)
def requantize(ctx):
    x = ctx.input("Input")
    s_in = ctx.attr("Scale_in", 1.0)
    s_out = ctx.attr("Scale_out", 1.0)
    return {"Output": jnp.clip(
        jnp.round(x.astype(jnp.float32) * (s_out / s_in)), -128, 127)
        .astype(jnp.int8)}
