"""File-driven data feeding for AsyncExecutor.

Parity: reference framework/data_feed.h (DataFeed :49, MultiSlotDataFeed
:224) + data_feed.proto (DataFeedDesc: batch_size + multi_slot_desc with
per-slot name/type/is_dense/is_used).

File format (the reference's MultiSlot text format): one sample per
line; for each slot in order: `<count> v1 v2 ... vcount`. uint64 slots
parse as int64 ids, float slots as float32.

TPU adaptation: sparse slots batch into a dense [B, maxlen] padded
int64 array (pad 0) — the LoD-free encoding the rest of the stack uses
(segment lengths ride along for sequence_pool via bind_seq_len).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

__all__ = ["DataFeedDesc", "MultiSlotDataFeed"]


class _Slot:
    def __init__(self, name: str, type: str = "uint64",
                 is_dense: bool = False, is_used: bool = True,
                 dim: int = 1):
        self.name = name
        self.type = type
        self.is_dense = is_dense
        self.is_used = is_used
        self.dim = dim


class DataFeedDesc:
    """Feed configuration (reference python/paddle/fluid/
    data_feed_desc.py wraps the protobuf; here a dict/JSON config with
    the same fields)."""

    def __init__(self, proto_or_path=None):
        self.batch_size = 32
        self.pipe_command = None
        self.slots: List[_Slot] = []
        if proto_or_path is None:
            return
        if isinstance(proto_or_path, dict):
            cfg = proto_or_path
        else:
            with open(proto_or_path) as f:
                cfg = json.load(f)
        self.batch_size = cfg.get("batch_size", 32)
        for s in cfg.get("slots", []):
            self.slots.append(_Slot(**s))

    def set_batch_size(self, bs: int):
        self.batch_size = bs

    def add_slot(self, name: str, type: str = "uint64",
                 is_dense: bool = False, dim: int = 1):
        self.slots.append(_Slot(name, type, is_dense, True, dim))
        return self

    def set_dense_slots(self, names: List[str]):
        for s in self.slots:
            if s.name in names:
                s.is_dense = True

    def set_use_slots(self, names: List[str]):
        for s in self.slots:
            s.is_used = s.name in names

    def desc(self) -> str:
        return json.dumps({
            "batch_size": self.batch_size,
            "slots": [vars(s) for s in self.slots]}, indent=2)


def _pad_ragged(vals, dtype):
    """Pad variable-length rows to a power-of-two bucket so the
    executor's shape-keyed jit cache reuses a handful of compiled
    programs instead of one per distinct maxlen."""
    maxlen = max(1, max(len(v) for v in vals))
    b = 4
    while b < maxlen:
        b *= 2
    arr = np.zeros((len(vals), b), dtype)
    for i, v in enumerate(vals):
        arr[i, :len(v)] = v
    return arr


class MultiSlotDataFeed:
    """Parse MultiSlot text files into padded batches (reference
    MultiSlotDataFeed::ParseOneInstance data_feed.cc)."""

    def __init__(self, desc: DataFeedDesc):
        self.desc = desc

    def _parse_line(self, line: str):
        toks = line.split()
        pos = 0
        sample = {}
        for slot in self.desc.slots:
            if pos >= len(toks):
                raise ValueError(
                    f"MultiSlot parse error: line ended before slot "
                    f"{slot.name!r}")
            n = int(toks[pos])
            pos += 1
            vals = toks[pos:pos + n]
            if len(vals) != n:
                raise ValueError(
                    f"MultiSlot parse error: slot {slot.name!r} "
                    f"declares {n} values, found {len(vals)}")
            pos += n
            if slot.is_used:
                if slot.type.startswith("float"):
                    sample[slot.name] = np.asarray(vals, np.float32)
                else:
                    sample[slot.name] = np.asarray(vals, np.int64)
        return sample

    def _batchify(self, samples: List[Dict]) -> Dict[str, np.ndarray]:
        out = {}
        for slot in self.desc.slots:
            if not slot.is_used:
                continue
            vals = [s[slot.name] for s in samples]
            if slot.is_dense:
                # dense slots have a fixed width: a ragged batch means
                # corrupt input, and np.stack raising is the loud
                # failure the reference's CheckFile gives
                out[slot.name] = np.stack(vals).astype(
                    np.float32 if slot.type.startswith("float")
                    else np.int64)
            else:
                # variable-length sparse slot (int or float): ALWAYS
                # pad + @SEQ_LEN companion (layers/sequence.py
                # contract), keyed on the slot being sparse -- not on
                # whether this particular batch happens to be ragged --
                # so the output schema is batch-content-independent
                dtype = (np.float32 if slot.type.startswith("float")
                         else np.int64)
                out[slot.name] = _pad_ragged(vals, dtype)
                out[slot.name + "@SEQ_LEN"] = np.asarray(
                    [len(v) for v in vals], np.int32)
        return out

    def read_batches(self, filename: str):
        """Yield feed dicts of batch_size samples from one file."""
        bs = self.desc.batch_size
        buf: List[Dict] = []
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                buf.append(self._parse_line(line))
                if len(buf) == bs:
                    yield self._batchify(buf)
                    buf = []
        if buf:
            yield self._batchify(buf)
