"""dygraph.Layer base (reference python/paddle/fluid/dygraph/layers.py)."""
from __future__ import annotations

from typing import Dict, Iterator

import numpy as np

from .. import unique_name
from .base import VarBase


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._full_name = unique_name.generate(
            name_scope or self.__class__.__name__.lower())
        self._dtype = dtype
        self._parameters: Dict[str, VarBase] = {}
        self._sub_layers: Dict[str, "Layer"] = {}

    def full_name(self):
        return self._full_name

    def create_parameter(self, shape, dtype=None, attr=None,
                         is_bias=False, default_initializer=None):
        from ..core.types import to_np_dtype

        np_dtype = to_np_dtype(dtype or self._dtype)
        shape = [int(s) for s in shape]
        if default_initializer is not None:
            val = _run_initializer(default_initializer, shape, np_dtype)
        elif is_bias:
            val = np.zeros(shape, dtype=np_dtype)
        else:
            fan_in = shape[0] if shape else 1
            fan_out = shape[1] if len(shape) > 1 else 1
            limit = np.sqrt(6.0 / (fan_in + fan_out))
            val = np.random.uniform(-limit, limit,
                                    shape).astype(np_dtype)
        name = (getattr(attr, "name", None)
                or unique_name.generate(self._full_name + ".w"))
        p = VarBase(val, name=name, persistable=True)
        self._parameters[name] = p
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def sublayers(self, include_sublayers=True):
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def state_dict(self, include_sublayers=True):
        return {p.name: p.numpy() for p in
                self.parameters(include_sublayers)}

    def set_dict(self, state, include_sublayers=True):
        import jax.numpy as jnp

        for p in self.parameters(include_sublayers):
            if p.name in state:
                p.value = jnp.asarray(state[p.name])

    load_dict = set_dict

    def train(self):
        self._is_test = False

    def eval(self):
        self._is_test = True

    def __setattr__(self, name, value):
        if isinstance(value, Layer):
            self.__dict__.setdefault("_sub_layers", {})[name] = value
        object.__setattr__(self, name, value)

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def forward(self, *args, **kwargs):
        raise NotImplementedError


def _run_initializer(init, shape, np_dtype):
    """Run a graph-mode Initializer eagerly for dygraph params."""
    from ..initializer import (ConstantInitializer, NormalInitializer,
                               NumpyArrayInitializer,
                               TruncatedNormalInitializer,
                               UniformInitializer, XavierInitializer,
                               MSRAInitializer)

    rng = np.random
    if isinstance(init, ConstantInitializer):
        return np.full(shape, init.value, dtype=np_dtype)
    if isinstance(init, UniformInitializer):
        return rng.uniform(init.low, init.high, shape).astype(np_dtype)
    if isinstance(init, NormalInitializer):
        return rng.normal(init.loc, init.scale, shape).astype(np_dtype)
    if isinstance(init, TruncatedNormalInitializer):
        v = rng.normal(init.loc, init.scale, shape)
        v = np.clip(v, init.loc - 2 * init.scale,
                    init.loc + 2 * init.scale)
        return v.astype(np_dtype)
    if isinstance(init, NumpyArrayInitializer):
        return np.asarray(init.value, dtype=np_dtype).reshape(shape)
    if isinstance(init, (XavierInitializer, MSRAInitializer)):
        fan_in = shape[0] if shape else 1
        fan_out = shape[1] if len(shape) > 1 else 1
        limit = np.sqrt(6.0 / (fan_in + fan_out))
        return rng.uniform(-limit, limit, shape).astype(np_dtype)
    raise TypeError(f"unsupported initializer for dygraph: {init}")


class PyLayer(Layer):
    """Custom autograd function for dygraph (reference
    dygraph/layers.py PyLayer / imperative py_layer): subclass defines
    numpy static methods ``forward(*inputs)`` and
    ``backward(*output_grads)``; calling the instance runs forward
    eagerly and records a py_func op on the tape so run_backward routes
    output grads through the user's backward (ops/host_ops.py
    py_func_grad with all x/out positions skipped — the reference
    PyLayer backward also sees only douts)."""

    def __init__(self):
        super().__init__()

    @staticmethod
    def forward(*inputs):
        raise NotImplementedError("PyLayer subclasses implement "
                                  "forward as a @staticmethod")

    @staticmethod
    def backward(*output_grads):
        raise NotImplementedError("PyLayer subclasses implement "
                                  "backward as a @staticmethod")

    @classmethod
    def _callable_ids(cls):
        # register the staticmethods themselves — register_py_func is
        # idempotent per function object, so repeated instantiation
        # does not grow the registry
        from ..ops.host_ops import register_py_func

        return (register_py_func(cls.forward),
                register_py_func(cls.backward))

    def __call__(self, *inputs):
        import numpy as np

        from ..core.program import Operator
        from .base import VarBase, to_variable, tracer

        ins = [to_variable(v) for v in inputs]
        outs = type(self).forward(*[v.numpy() for v in ins])
        if not isinstance(outs, (list, tuple)):
            outs = (outs,)
        out_vars = [VarBase(np.asarray(o)) for o in outs]
        t = tracer()
        if t is not None and t._record:
            fid, bid = self._callable_ids()
            in_names = [v.name for v in ins]
            out_names = [v.name for v in out_vars]
            op = Operator(None, "py_func",
                          {"X": in_names}, {"Out": out_names},
                          {"forward_callable_id": fid,
                           "backward_callable_id": bid,
                           # reference PyLayer.backward sees douts only
                           "backward_skip_vars": in_names + out_names})
            t.record(op, {"X": ins}, {"Out": out_vars})
        return out_vars if len(out_vars) > 1 else out_vars[0]
