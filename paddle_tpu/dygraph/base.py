"""Imperative (dygraph) mode: eager execution with a gradient tape.

Parity: reference paddle/fluid/imperative/ (VarBase layer.h:115, OpBase,
Tracer tracer.cc:138, autograd engine.cc) + python/paddle/fluid/dygraph.
JAX is natively eager, so ops run immediately through the SAME registered
kernels as graph mode; a lightweight tape records (op, inputs, outputs)
and backward() replays it through the registry's vjp-derived grad kernels
-- one autodiff implementation for both modes, where the reference
maintains two.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import unique_name
from ..core.program import GRAD_SUFFIX, Operator, grad_var_name
from ..core.registry import (EMPTY_VAR, get_op_info, make_grad_ops,
                             run_op)

_dygraph_tracer = None


def enabled():
    return _dygraph_tracer is not None


def enable_dygraph(place=None):
    global _dygraph_tracer
    from .tracer import Tracer

    _dygraph_tracer = Tracer()


def disable_dygraph():
    global _dygraph_tracer
    _dygraph_tracer = None


def tracer():
    return _dygraph_tracer


@contextlib.contextmanager
def guard(place=None):
    enable_dygraph(place)
    try:
        yield
    finally:
        disable_dygraph()


@contextlib.contextmanager
def no_grad():
    t = tracer()
    old = t._record if t else True
    if t:
        t._record = False
    try:
        yield
    finally:
        if t:
            t._record = old


class VarBase:
    """Eager tensor + optional grad (reference imperative/layer.h:115)."""

    def __init__(self, value, name=None, stop_gradient=False,
                 persistable=False):
        self.value = jnp.asarray(value)
        self.name = name or unique_name.generate("dyvar")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = not stop_gradient
        self._grad = None

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        from ..core.types import as_datatype

        return as_datatype(self.value.dtype.name)

    def numpy(self):
        return np.asarray(self.value)

    def gradient(self):
        return None if self._grad is None else np.asarray(self._grad)

    @property
    def grad(self):
        return self._grad

    def clear_gradient(self):
        self._grad = None

    def backward(self):
        t = tracer()
        if t is None:
            raise RuntimeError("backward() outside dygraph.guard()")
        t.run_backward(self)

    def detach(self):
        return VarBase(self.value, stop_gradient=True)

    def astype(self, dtype):
        from ..core.types import to_jnp_dtype

        return VarBase(self.value.astype(to_jnp_dtype(dtype)))

    # arithmetic sugar routed through traced ops so grads flow
    def _ew(self, other, op_type, reverse=False):
        if not isinstance(other, VarBase):
            other = VarBase(jnp.asarray(other, dtype=self.value.dtype),
                            stop_gradient=True)
        a, b = (other, self) if reverse else (self, other)
        return trace_op(op_type, {"X": [a], "Y": [b]}, 1, {})[0]

    def __add__(self, o):
        return self._ew(o, "elementwise_add")

    def __radd__(self, o):
        return self._ew(o, "elementwise_add", True)

    def __sub__(self, o):
        return self._ew(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._ew(o, "elementwise_sub", True)

    def __mul__(self, o):
        return self._ew(o, "elementwise_mul")

    def __rmul__(self, o):
        return self._ew(o, "elementwise_mul", True)

    def __truediv__(self, o):
        return self._ew(o, "elementwise_div")

    def __repr__(self):
        return f"VarBase(name={self.name}, shape={self.shape})"


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    return VarBase(value, name=name,
                   stop_gradient=not isinstance(value, VarBase))


def trace_op_into(op_type, inputs: Dict[str, List[VarBase]],
                  out_vars_by_slot: Dict[str, List[VarBase]],
                  attrs) -> None:
    """Run one op eagerly, filling CALLER-provided output VarBases.

    This is the `fluid.layers.*`-in-dygraph-mode path (reference
    framework.py:1633 Block.append_op traces through the dygraph tracer
    instead of appending): LayerHelper pre-creates the output VarBases
    it will return, so the trace must write into those objects."""
    t = tracer()
    env = {}
    in_names = {}
    for slot, vars_ in inputs.items():
        names = []
        for v in vars_:
            if v is None:
                continue
            env[v.name] = v.value
            names.append(v.name)
        if names:
            in_names[slot] = names
    out_names = {slot: [v.name for v in vs]
                 for slot, vs in out_vars_by_slot.items()}
    op = Operator(None, op_type, in_names, out_names, attrs)
    rng_cell = [t.next_rng() if t else jax.random.PRNGKey(0)]
    # remember the exact key: run_backward replays it so vjp grad
    # kernels (which recompute the forward) re-toss IDENTICAL noise
    op._dygraph_rng_key = rng_cell[0]
    run_op(op, env, rng_cell=rng_cell, rng_salt=0)
    for slot, vs in out_vars_by_slot.items():
        for v in vs:
            v.value = jnp.asarray(env[v.name])
    if t is not None and t._record:
        t.record(op, inputs, out_vars_by_slot)


def trace_op(op_type, inputs: Dict[str, List[VarBase]], num_outputs,
             attrs, out_slots=None) -> List[VarBase]:
    """Run one op eagerly + record it on the tape."""
    if out_slots is None:
        out_slots = {"Out": num_outputs}
    out_vars_by_slot = {
        slot: [VarBase(0.0, name=unique_name.generate(
            f"{op_type}.{slot}")) for _ in range(n)]
        for slot, n in out_slots.items()}
    trace_op_into(op_type, inputs, out_vars_by_slot, attrs)
    return [v for vs in out_vars_by_slot.values() for v in vs]
