"""dygraph NN layers (reference python/paddle/fluid/dygraph/nn.py:
Conv2D, Pool2D, FC, BatchNorm, Embedding, LayerNorm, GRUUnit...).
Each forward() routes through trace_op so the tape records grads."""
from __future__ import annotations

import numpy as np

from ..initializer import ConstantInitializer
from .base import VarBase, trace_op
from .layers import Layer


class Conv2D(Layer):
    def __init__(self, name_scope=None, num_channels=None,
                 num_filters=None, filter_size=3, stride=1, padding=0,
                 dilation=1, groups=1, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        fs = filter_size if isinstance(filter_size, (list, tuple)) \
            else [filter_size, filter_size]
        self._attrs = {"strides": _pair(stride), "paddings": _pair(padding),
                       "dilations": _pair(dilation), "groups": groups}
        self._act = act
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups, fs[0], fs[1]])
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_filters], is_bias=True)

    def forward(self, x):
        out, = trace_op("conv2d", {"Input": [x], "Filter": [self.weight]},
                        1, self._attrs, out_slots={"Output": 1})
        if self.bias is not None:
            out, = trace_op("elementwise_add",
                            {"X": [out], "Y": [self.bias]}, 1,
                            {"axis": 1})
        return _act(out, self._act)


class Pool2D(Layer):
    def __init__(self, name_scope=None, pool_size=2, pool_type="max",
                 pool_stride=2, pool_padding=0, global_pooling=False,
                 ceil_mode=False, exclusive=True):
        super().__init__(name_scope)
        self._attrs = {"pooling_type": pool_type,
                       "ksize": _pair(pool_size),
                       "strides": _pair(pool_stride),
                       "paddings": _pair(pool_padding),
                       "global_pooling": global_pooling,
                       "ceil_mode": ceil_mode, "exclusive": exclusive}

    def forward(self, x):
        out, = trace_op("pool2d", {"X": [x]}, 1, self._attrs)
        return out


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(None, dtype)
        self._act = act
        self.weight = self.create_parameter([input_dim, output_dim],
                                            attr=param_attr)
        self.bias = None if bias_attr is False else \
            self.create_parameter([output_dim], is_bias=True)

    def forward(self, x):
        out, = trace_op("mul", {"X": [x], "Y": [self.weight]}, 1,
                        {"x_num_col_dims": max(1, len(x.shape) - 1),
                         "y_num_col_dims": 1})
        if self.bias is not None:
            out, = trace_op("elementwise_add",
                            {"X": [out], "Y": [self.bias]}, 1,
                            {"axis": -1})
        return _act(out, self._act)


class FC(Layer):
    """fluid-era FC (flattens input from num_flatten_dims)."""

    def __init__(self, name_scope=None, size=None, num_flatten_dims=1,
                 param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._nfd = num_flatten_dims
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None

    def forward(self, x):
        if self.weight is None:
            in_dim = int(np.prod(x.shape[self._nfd:]))
            self.weight = self.create_parameter([in_dim, self._size],
                                                attr=self._param_attr)
            self.bias = None if self._bias_attr is False else \
                self.create_parameter([self._size], is_bias=True)
        out, = trace_op("mul", {"X": [x], "Y": [self.weight]}, 1,
                        {"x_num_col_dims": self._nfd,
                         "y_num_col_dims": 1})
        if self.bias is not None:
            out, = trace_op("elementwise_add",
                            {"X": [out], "Y": [self.bias]}, 1,
                            {"axis": -1})
        return _act(out, self._act)


class BatchNorm(Layer):
    def __init__(self, name_scope=None, num_channels=None, act=None,
                 is_test=False, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", use_global_stats=False):
        super().__init__(name_scope, dtype)
        c = num_channels
        self._act = act
        self._attrs = {"momentum": momentum, "epsilon": epsilon,
                       "is_test": is_test, "data_layout": data_layout,
                       "use_global_stats": use_global_stats}
        self.weight = self.create_parameter(
            [c], default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([c], is_bias=True)
        self._mean = VarBase(np.zeros([c], dtype=np.float32),
                             stop_gradient=True, persistable=True)
        self._variance = VarBase(np.ones([c], dtype=np.float32),
                                 stop_gradient=True, persistable=True)

    def forward(self, x):
        outs = trace_op(
            "batch_norm",
            {"X": [x], "Scale": [self.weight], "Bias": [self.bias],
             "Mean": [self._mean], "Variance": [self._variance]},
            5, self._attrs,
            out_slots={"Y": 1, "MeanOut": 1, "VarianceOut": 1,
                       "SavedMean": 1, "SavedVariance": 1})
        y, mean_out, var_out = outs[0], outs[1], outs[2]
        self._mean.value = mean_out.value
        self._variance.value = var_out.value
        return _act(y, self._act)


class Embedding(Layer):
    def __init__(self, name_scope=None, size=None, is_sparse=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._padding_idx = -1 if padding_idx is None else padding_idx
        self.weight = self.create_parameter(size, attr=param_attr)

    def forward(self, ids):
        out, = trace_op("lookup_table",
                        {"Ids": [ids], "W": [self.weight]}, 1,
                        {"padding_idx": self._padding_idx})
        return out


class LayerNorm(Layer):
    def __init__(self, name_scope=None, normalized_shape=None, scale=True,
                 shift=True, begin_norm_axis=1, epsilon=1e-5,
                 param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        dim = int(np.prod(normalized_shape)) \
            if normalized_shape is not None else None
        self._attrs = {"epsilon": epsilon,
                       "begin_norm_axis": begin_norm_axis}
        self._act = act
        self.weight = self.create_parameter(
            [dim], default_initializer=ConstantInitializer(1.0)) \
            if scale and dim else None
        self.bias = self.create_parameter([dim], is_bias=True) \
            if shift and dim else None

    def forward(self, x):
        ins = {"X": [x]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        outs = trace_op("layer_norm", ins, 3, self._attrs,
                        out_slots={"Y": 1, "Mean": 1, "Variance": 1})
        return _act(outs[0], self._act)


class GRUUnit(Layer):
    def __init__(self, name_scope=None, size=None, param_attr=None,
                 bias_attr=None, activation="tanh",
                 gate_activation="sigmoid", origin_mode=False,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        h = size // 3
        self._attrs = {"activation": activation,
                       "gate_activation": gate_activation,
                       "origin_mode": origin_mode}
        self.weight = self.create_parameter([h, 3 * h])
        self.bias = self.create_parameter([1, 3 * h], is_bias=True)

    def forward(self, input, hidden):
        outs = trace_op(
            "gru_unit",
            {"Input": [input], "HiddenPrev": [hidden],
             "Weight": [self.weight], "Bias": [self.bias]},
            3, self._attrs,
            out_slots={"Gate": 1, "ResetHiddenPrev": 1, "Hidden": 1})
        return outs[2], outs[1], outs[0]


class PRelu(Layer):
    def __init__(self, name_scope=None, mode="all", channel=None,
                 input_shape=None, param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._mode = mode
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [1, channel, 1, 1]
        else:
            shape = [1] + list(input_shape[1:])
        self.weight = self.create_parameter(
            shape, default_initializer=ConstantInitializer(0.25))

    def forward(self, x):
        out, = trace_op("prelu", {"X": [x], "Alpha": [self.weight]}, 1,
                        {"mode": self._mode})
        return out


class NCE(Layer):
    """Noise-contrastive estimation head (reference dygraph/nn.py NCE
    signature): weight/bias are created lazily at first forward from
    the input width (the reference's _build_once), so `dim` needs no
    extra positional argument."""

    def __init__(self, name_scope=None, num_total_classes=None,
                 sample_weight=None, param_attr=None, bias_attr=None,
                 num_neg_samples=None, sampler="uniform",
                 custom_dist=None, seed=0, is_sparse=False,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        if num_total_classes is None:
            raise ValueError("dygraph NCE needs num_total_classes")
        if sampler != "uniform" or custom_dist is not None:
            raise ValueError("dygraph NCE: only the uniform sampler is "
                             "lowered (reference nce_op.h default)")
        self._num_total_classes = int(num_total_classes)
        self._num_neg = int(num_neg_samples
                            if num_neg_samples is not None else 10)
        self._seed = int(seed)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._sample_weight = sample_weight
        self.weight = None
        self.bias = None

    def _build_once(self, input):
        dim = int(input.shape[-1])
        self.weight = self.create_parameter(
            [self._num_total_classes, dim], attr=self._param_attr)
        if self._bias_attr is not False:
            self.bias = self.create_parameter(
                [self._num_total_classes], attr=self._bias_attr,
                is_bias=True)

    def forward(self, input, label):
        if self.weight is None:
            self._build_once(input)
        ins = {"Input": [input], "Label": [label],
               "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        if self._sample_weight is not None:
            ins["SampleWeight"] = [self._sample_weight]
        outs = trace_op(
            "nce", ins, 3,
            {"num_total_classes": self._num_total_classes,
             "num_neg_samples": self._num_neg, "seed": self._seed},
            out_slots={"Cost": 1, "SampleLogits": 1,
                       "SampleLabels": 1})
        return outs[0]


class Dropout(Layer):
    def __init__(self, p=0.5, mode="downgrade_in_infer"):
        super().__init__()
        self._p = p
        self._mode = mode
        self._is_test = False

    def forward(self, x):
        outs = trace_op("dropout", {"X": [x]}, 2,
                        {"dropout_prob": self._p,
                         "is_test": getattr(self, "_is_test", False),
                         "dropout_implementation": self._mode},
                        out_slots={"Out": 1, "Mask": 1})
        return outs[0]


def _act(v, act):
    if act is None:
        return v
    out, = trace_op(act, {"X": [v]}, 1, {})
    return out


def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v]


class BilinearTensorProduct(Layer):
    """reference dygraph/nn.py BilinearTensorProduct:1025 —
    out[b,k] = x[b] @ W[k] @ y[b] + bias[k]."""

    def __init__(self, name_scope=None, input1_dim=None,
                 input2_dim=None, output_dim=None, act=None,
                 param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim])
        self.bias = None if bias_attr is False else \
            self.create_parameter([1, output_dim], is_bias=True)

    def forward(self, x, y):
        ins = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out, = trace_op("bilinear_tensor_product", ins, 1, {})
        return _act(out, self._act)


class Conv2DTranspose(Layer):
    """reference dygraph/nn.py Conv2DTranspose:1117 — filter layout
    [in_c, out_c/groups, kh, kw] (conv2d_transpose_op.cc)."""

    def __init__(self, name_scope=None, num_channels=None,
                 num_filters=None, filter_size=3, stride=1, padding=0,
                 dilation=1, groups=1, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        fs = _pair(filter_size)
        self._attrs = {"strides": _pair(stride),
                       "paddings": _pair(padding),
                       "dilations": _pair(dilation), "groups": groups}
        self._act = act
        self.weight = self.create_parameter(
            [num_channels, num_filters // groups, fs[0], fs[1]])
        self.bias = None if bias_attr is False else \
            self.create_parameter([num_filters], is_bias=True)

    def forward(self, x):
        out, = trace_op("conv2d_transpose",
                        {"Input": [x], "Filter": [self.weight]}, 1,
                        self._attrs, out_slots={"Output": 1})
        if self.bias is not None:
            out, = trace_op("elementwise_add",
                            {"X": [out], "Y": [self.bias]}, 1,
                            {"axis": 1})
        return _act(out, self._act)


class SequenceConv(Layer):
    """reference dygraph/nn.py SequenceConv:1329 — context-window conv
    over padded [B,T,D] batches (the @SEQ_LEN design replaces LoD; in
    eager mode rows are taken full-length)."""

    def __init__(self, name_scope=None, num_filters=None,
                 filter_size=3, filter_stride=1, padding=None,
                 input_dim=None, act=None, param_attr=None,
                 bias_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        if filter_stride != 1:
            raise ValueError("sequence_conv supports stride 1 only "
                             "(reference sequence_conv_op.cc)")
        self._attrs = {"contextLength": filter_size,
                       "contextStart": -((filter_size - 1) // 2),
                       "contextStride": 1}
        self._act = act
        self.weight = self.create_parameter(
            [filter_size * input_dim, num_filters])
        self.bias = None if bias_attr is False else \
            self.create_parameter([num_filters], is_bias=True)

    def forward(self, x):
        out, = trace_op("sequence_conv",
                        {"X": [x], "Filter": [self.weight]}, 1,
                        self._attrs)
        if self.bias is not None:
            out, = trace_op("elementwise_add",
                            {"X": [out], "Y": [self.bias]}, 1,
                            {"axis": 2})
        return _act(out, self._act)
