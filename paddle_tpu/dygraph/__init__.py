from .base import (to_variable, guard, enabled, enable_dygraph,
                   disable_dygraph, no_grad)  # noqa: F401
from .layers import Layer, PyLayer  # noqa: F401
from .nn import (Conv2D, Pool2D, FC, Linear, BatchNorm, Embedding,
                 LayerNorm, GRUUnit, PRelu, NCE, Dropout,
                 BilinearTensorProduct, Conv2DTranspose,
                 SequenceConv)  # noqa: F401
from .checkpoint import save_persistables, load_persistables  # noqa: F401
from .tracer import Tracer  # noqa: F401
