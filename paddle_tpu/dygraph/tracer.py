"""Dygraph tape + backward engine (reference imperative/tracer.cc:138 +
engine.cc). Replays recorded ops in reverse through the registry's grad
makers -- the same machinery graph-mode append_backward uses."""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from ..core.program import GRAD_SUFFIX, grad_var_name
from ..core.registry import EMPTY_VAR, make_grad_ops, run_op


class Tracer:
    def __init__(self):
        self._tape = []  # (op_desc, input VarBases, output VarBases)
        self._record = True
        self._rng = jax.random.PRNGKey(0)

    def next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def record(self, op, inputs, outputs):
        self._tape.append((op, inputs, outputs))

    def reset(self):
        self._tape.clear()

    def run_backward(self, loss):
        env: Dict = {}
        var_by_name = {}
        for op, inputs, outputs in self._tape:
            for vs in inputs.values():
                for v in vs:
                    if v is not None:
                        env[v.name] = v.value
                        var_by_name[v.name] = v
            for vs in outputs.values():
                for v in vs:
                    env[v.name] = v.value
                    var_by_name[v.name] = v
        grads_env = {grad_var_name(loss.name):
                     jnp.ones_like(loss.value)}
        produced = {grad_var_name(loss.name)}
        for op, inputs, outputs in reversed(self._tape):
            out_names = [n for ns in op.outputs.values() for n in ns]
            if not any(grad_var_name(n) in produced for n in out_names):
                continue
            no_grad = {v.name for vs in inputs.values() for v in vs
                       if v is not None and v.stop_gradient}
            for gop in make_grad_ops(op, no_grad_set=no_grad):
                run_env = dict(env)
                for slot, names in list(gop.inputs.items()):
                    if slot.endswith(GRAD_SUFFIX):
                        resolved = []
                        for n in names:
                            if n in produced:
                                run_env[n] = grads_env[n]
                                resolved.append(n)
                            else:
                                resolved.append(EMPTY_VAR)
                        gop.inputs[slot] = resolved
                try:
                    # same key+salt as the forward trace: sampling ops'
                    # vjp recomputation must see the forward's noise
                    run_op(gop, run_env,
                           rng_cell=[getattr(op, "_dygraph_rng_key",
                                             None)
                                     if getattr(op, "_dygraph_rng_key",
                                                None) is not None
                                     else jax.random.PRNGKey(0)],
                           rng_salt=0)
                except KeyError:
                    continue
                for slot, names in gop.outputs.items():
                    for n in names:
                        if n not in run_env:
                            continue
                        g = run_env[n]
                        if n in produced:
                            grads_env[n] = grads_env[n] + g
                        else:
                            grads_env[n] = g
                            produced.add(n)
        # write grads back onto VarBases
        for name, var in var_by_name.items():
            g = grads_env.get(grad_var_name(name))
            if g is not None and not var.stop_gradient:
                if var._grad is None:
                    var._grad = g
                else:
                    var._grad = var._grad + g
        self.reset()
