"""dygraph checkpoint (reference python/paddle/fluid/dygraph/checkpoint.py)."""
from __future__ import annotations

import os

import numpy as np


def save_persistables(model_dict, dirname, optimizers=None):
    os.makedirs(dirname, exist_ok=True)
    state = model_dict.state_dict() if hasattr(model_dict, "state_dict") \
        else {k: v.numpy() for k, v in model_dict.items()}
    np.savez(os.path.join(dirname, "params.npz"), **state)


def load_persistables(model_or_dirname, dirname=None):
    if dirname is None:
        dirname = model_or_dirname
        with np.load(os.path.join(dirname, "params.npz")) as blob:
            return {k: blob[k] for k in blob.files}, {}
    with np.load(os.path.join(dirname, "params.npz")) as blob:
        model_or_dirname.set_dict({k: blob[k] for k in blob.files})
    return model_or_dirname
