"""IR graph + pass framework over Programs.

Parity: reference paddle/fluid/framework/ir/ (ir::Graph graph.h:72,
ir::Pass pass.h:34, GraphPatternDetector graph_pattern_detector.h, and
the fuse passes: conv_bn_fuse_pass.cc, fc_fuse_pass.cc, ...).

TPU-first design note: XLA already performs elementwise/matmul fusion,
layout assignment and buffer reuse at compile time, so the reference's
~25 kernel-fusion passes largely collapse into the compiler. The passes
that still pay off at the *program* level — and are implemented here —
are the ones XLA cannot do because they change program structure or
parameter values:
  - conv_bn_fuse: folds inference-mode batch_norm into conv weights
    (removes the BN subgraph and its 4 param tensors entirely),
  - fc_fuse: mul + elementwise_add (+act) -> one fc op (fewer program
    ops to trace; XLA sees one fused dot ladder),
  - dropout_eliminate: removes is_test dropout ops and their mask
    computation from the serving program.
The Graph/Pass/registry surface mirrors the reference so tooling
(viz, custom passes) has the same entry points.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from .core.program import Block, Operator, Program

__all__ = ["Graph", "Node", "Pass", "register_pass", "get_pass",
           "apply_passes", "PassRegistry"]


class Node:
    """Graph node: either an op or a var (reference ir/node.h)."""

    def __init__(self, kind: str, name: str, op: Optional[Operator] = None):
        self.kind = kind  # "op" | "var"
        self.name = name
        self.op = op
        self.inputs: List["Node"] = []
        self.outputs: List["Node"] = []

    def is_op(self):
        return self.kind == "op"

    def is_var(self):
        return self.kind == "var"

    def __repr__(self):
        return f"Node({self.kind}:{self.name})"


class Graph:
    """Dependency graph of one Block (reference ir/graph.h:72).

    Var nodes are SSA-versioned: each write creates a fresh var node, so
    consumers link to the exact producing op (the reference achieves the
    same with unique ir::Node instances per var occurrence).
    """

    def __init__(self, program: Program, block_idx: int = 0):
        self.program = program
        self.block: Block = program.blocks[block_idx]
        self.attrs: Dict = {}
        self.rebuild()

    def rebuild(self):
        self.op_nodes: List[Node] = []
        self.var_nodes: List[Node] = []
        latest: Dict[str, Node] = {}
        for op in self.block.ops:
            on = Node("op", op.type, op)
            self.op_nodes.append(on)
            for name in op.input_arg_names:
                vn = latest.get(name)
                if vn is None:
                    vn = Node("var", name)
                    self.var_nodes.append(vn)
                    latest[name] = vn
                vn.outputs.append(on)
                on.inputs.append(vn)
            for name in op.output_arg_names:
                vn = Node("var", name)
                self.var_nodes.append(vn)
                latest[name] = vn
                vn.inputs.append(on)
                on.outputs.append(vn)
        self._latest = latest

    # --- query helpers (GraphPatternDetector-style) -------------------
    def producer(self, op: Operator, slot: str) -> Optional[Operator]:
        """The op producing `op.inputs[slot][0]`, or None if it's a
        feed/param."""
        names = op.input(slot)
        if not names:
            return None
        target = names[0]
        idx = self.block.ops.index(op)
        for prev in reversed(self.block.ops[:idx]):
            if target in prev.output_arg_names:
                return prev
        return None

    def consumers(self, op: Operator, var_name: str) -> List[Operator]:
        """Ops after `op` reading var_name (before any re-write of it)."""
        idx = self.block.ops.index(op)
        out = []
        for nxt in self.block.ops[idx + 1:]:
            if var_name in nxt.input_arg_names:
                out.append(nxt)
            if var_name in nxt.output_arg_names:
                break
        return out

    # --- mutation helpers ---------------------------------------------
    def remove_op(self, op: Operator):
        self.block.ops.remove(op)

    def replace_input_everywhere(self, old: str, new: str,
                                 after: Optional[Operator] = None):
        start = 0 if after is None else self.block.ops.index(after) + 1
        for op in self.block.ops[start:]:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [new if n == old else n for n in names]

    def to_program(self) -> Program:
        return self.program


class Pass:
    """Base pass (reference ir/pass.h:34). Subclass and implement
    apply_impl(graph, scope)."""

    name = "pass"

    def apply(self, graph: Graph, scope=None) -> Graph:
        self.apply_impl(graph, scope)
        graph.rebuild()
        # any rewrite invalidates warm Executor caches keyed on
        # (id(program), program._version, ...) -- removal/rewire-only
        # passes would otherwise serve the stale pre-pass executable
        graph.program._version += 1
        return graph

    def apply_impl(self, graph: Graph, scope) -> None:
        raise NotImplementedError


class PassRegistry:
    _passes: Dict[str, Callable[[], Pass]] = {}

    @classmethod
    def register(cls, name: str, factory: Callable[[], Pass]):
        cls._passes[name] = factory

    @classmethod
    def get(cls, name: str) -> Pass:
        if name not in cls._passes:
            raise KeyError(f"pass {name!r} is not registered; have "
                           f"{sorted(cls._passes)}")
        return cls._passes[name]()

    @classmethod
    def has(cls, name: str) -> bool:
        return name in cls._passes


def register_pass(name: str):
    def deco(klass):
        klass.name = name
        PassRegistry.register(name, klass)
        return klass

    return deco


def get_pass(name: str) -> Pass:
    return PassRegistry.get(name)


def apply_passes(program: Program, pass_names: List[str], scope=None,
                 block_idx: int = 0, protected=None) -> Program:
    """`protected` vars (e.g. the predictor's fetch targets) must still
    be produced by the rewritten program — passes may not erase them."""
    graph = Graph(program, block_idx)
    graph.attrs["protected"] = set(protected or ())
    for name in pass_names:
        get_pass(name).apply(graph, scope)
    return graph.to_program()


# =====================================================================
# Passes
# =====================================================================
@register_pass("dropout_eliminate_pass")
class DropoutEliminatePass(Pass):
    """Remove is_test dropout ops (reference: the AnalysisPredictor
    pipeline's simplification passes; dropout at inference is identity
    for upscale_in_train, x*(1-p) for downgrade_in_infer)."""

    def apply_impl(self, graph: Graph, scope):
        for op in list(graph.block.ops):
            if op.type != "dropout" or not op.attr("is_test", False):
                continue
            x, = op.input("X")
            out, = op.output("Out")
            impl = op.attr("dropout_implementation", "downgrade_in_infer")
            if impl == "upscale_in_train":
                if out in graph.attrs.get("protected", ()):
                    # fetched var must stay produced: identity instead
                    # of rewiring it away (XLA elides the copy)
                    idx = graph.block.ops.index(op)
                    graph.remove_op(op)
                    graph.block.insert_op(idx, "assign", {"X": [x]},
                                          {"Out": [out]}, {})
                    continue
                graph.replace_input_everywhere(out, x, after=op)
                graph.remove_op(op)
            else:
                idx = graph.block.ops.index(op)
                graph.remove_op(op)
                graph.block.insert_op(
                    idx, "scale", {"X": [x]}, {"Out": [out]},
                    {"scale": 1.0 - op.attr("dropout_prob", 0.5),
                     "bias": 0.0})


@register_pass("conv_bn_fuse_pass")
class ConvBNFusePass(Pass):
    """Fold inference batch_norm into the preceding conv2d's weights
    (reference ir/conv_bn_fuse_pass.cc). Requires the scope holding the
    parameter values:  w' = w * gamma/sqrt(var+eps)   (per out-channel)
                       b' = beta - mean * gamma/sqrt(var+eps)
    The BN op is replaced by an elementwise_add of the new bias."""

    def apply_impl(self, graph: Graph, scope):
        if scope is None:
            return
        for bn in list(graph.block.ops):
            if bn.type != "batch_norm":
                continue
            if not (bn.attr("is_test", False)
                    or bn.attr("use_global_stats", False)):
                continue
            conv = graph.producer(bn, "X")
            add = None  # conv_eltwiseadd_bn pattern (reference
            # ir/conv_elementwise_add_fuse_pass-era variant): the conv2d
            # layer emits a separate per-channel bias add
            if (conv is not None and conv.type == "elementwise_add"
                    and conv.attr("axis", -1) == 1):
                add = conv
                conv = graph.producer(add, "X")
            if conv is None or conv.type != "conv2d":
                continue
            conv_out, = conv.output("Output")
            mid = add.output("Out")[0] if add is not None else conv_out
            # conv (and add) output must feed only this chain/BN
            nxt = add if add is not None else bn
            if [c is nxt for c in graph.consumers(conv, conv_out)] != [True]:
                continue
            if add is not None and [
                    c is bn for c in graph.consumers(add, mid)] != [True]:
                continue
            w_name = conv.input("Filter")[0]
            w = scope._get(w_name)
            gamma = scope._get(bn.input("Scale")[0])
            beta = scope._get(bn.input("Bias")[0])
            mean = scope._get(bn.input("Mean")[0])
            var = scope._get(bn.input("Variance")[0])
            if any(v is None for v in (w, gamma, beta, mean, var)):
                continue
            b0 = None
            if add is not None:  # validate bias BEFORE mutating weights
                b0 = scope._get(add.input("Y")[0])
                if b0 is None:
                    continue
            eps = bn.attr("epsilon", 1e-5)
            w, gamma, beta, mean, var = map(np.asarray,
                                            (w, gamma, beta, mean, var))
            inv_std = gamma / np.sqrt(var + eps)
            scope._set(w_name,
                       (w * inv_std[:, None, None, None]).astype(w.dtype))
            bn_out, = bn.output("Y")
            if add is not None:
                # fold into the existing conv bias:
                # BN(conv+b0) = conv*s + (b0-mean)*s + beta
                b_name = add.input("Y")[0]
                b0 = np.asarray(b0)
                scope._set(b_name, ((b0.reshape(-1) - mean) * inv_std
                                    + beta).astype(b0.dtype).reshape(
                                        b0.shape))
                idx = graph.block.ops.index(bn)
                graph.remove_op(bn)
                if bn_out in graph.attrs.get("protected", ()):
                    graph.block.insert_op(idx, "assign", {"X": [mid]},
                                          {"Out": [bn_out]}, {})
                else:
                    graph.replace_input_everywhere(bn_out, mid)
            else:
                bias_name = w_name + "@bn_fused_bias"
                bias_val = (beta - mean * inv_std).astype(w.dtype)
                scope.var(bias_name)
                scope._set(bias_name, bias_val)
                graph.block.create_var(name=bias_name,
                                       shape=list(bias_val.shape),
                                       dtype=str(bias_val.dtype),
                                       persistable=True)
                idx = graph.block.ops.index(bn)
                graph.remove_op(bn)
                graph.block.insert_op(
                    idx, "elementwise_add",
                    {"X": [conv_out], "Y": [bias_name]},
                    {"Out": [bn_out]}, {"axis": 1})


@register_pass("fc_fuse_pass")
class FCFusePass(Pass):
    """mul + elementwise_add (+ relu) -> fc op (reference
    ir/fc_fuse_pass.cc). XLA fuses the arithmetic anyway; the win here
    is a smaller program (one traced op instead of three)."""

    def apply_impl(self, graph: Graph, scope):
        protected = graph.attrs.get("protected", set())
        i = 0
        # single forward sweep (no restart after a fuse): fusing at
        # position i only touches ops up to the optional act right
        # after the add, so continuing from i stays correct and keeps
        # the pass O(n^2) instead of O(n^3) on big serving programs
        while i < len(graph.block.ops):
            add = graph.block.ops[i]
            i += 1
            if add.type != "elementwise_add":
                continue
            mul = graph.producer(add, "X")
            if mul is None or mul.type != "mul":
                continue
            # Y must be a 1-D persistable bias param (reference
            # fc_fuse_pass.cc checks the same) — a residual add of
            # an activation is NOT an fc bias
            y_name = add.input("Y")[0]
            y_var = (graph.block.vars.get(y_name)
                     or graph.block._find_var_recursive(y_name))
            if (y_var is None or not y_var.persistable
                    or y_var.shape is None or len(y_var.shape) != 1):
                continue
            if graph.producer(add, "Y") is not None:
                continue
            mul_out, = mul.output("Out")
            if mul_out in protected:
                continue
            if [c is add for c in
                    graph.consumers(mul, mul_out)] != [True]:
                continue
            add_out, = add.output("Out")
            act = None
            consumers = graph.consumers(add, add_out)
            if (len(consumers) == 1 and consumers[0].type == "relu"
                    and add_out not in protected):
                act = consumers[0]
            out_name = act.output("Out")[0] if act else add_out
            idx = graph.block.ops.index(mul)
            for dead in ([mul, add] + ([act] if act else [])):
                graph.remove_op(dead)
            graph.block.insert_op(
                idx, "fc",
                {"Input": mul.input("X"), "W": mul.input("Y"),
                 "Bias": add.input("Y")},
                {"Out": [out_name]},
                {"in_num_col_dims": mul.attr("x_num_col_dims", 1),
                 "activation_type": "relu" if act else ""})
            i = idx  # continue right after the new fc op


@register_pass("attention_fuse_pass")
class AttentionFusePass(Pass):
    """Fuse the hand-written scaled-dot-product attention composition
    (reference nets.py scaled_dot_product_attention builds it from
    matmul/scale/softmax/dropout/matmul -- the reference has NO fused
    attention op) into the framework's `attention` op, which routes to
    the Pallas flash kernel / transpose-free XLA path (ops/nn_ops.py).

    Pattern on 4D [B,H,T,D] operands:
        matmul(Q, K, transpose_Y=True) [-> scale] -> softmax
        [-> dropout] -> matmul(., V)
    Every intermediate must have exactly one consumer and not be
    protected (fetched).
    """

    def apply_impl(self, graph: Graph, scope):
        protected = graph.attrs.get("protected", set())

        def sole_consumer(op, out_name):
            if out_name in protected:
                return None
            cons = graph.consumers(op, out_name)
            return cons[0] if len(cons) == 1 else None

        i = 0
        while i < len(graph.block.ops):
            qk = graph.block.ops[i]
            i += 1
            if qk.type != "matmul" or not qk.attr("transpose_Y", False) \
                    or qk.attr("transpose_X", False):
                continue
            qv = graph.block._find_var_recursive(qk.input("X")[0])
            if qv is None or qv.shape is None or len(qv.shape) != 4:
                continue
            scale = qk.attr("alpha", 1.0)
            cur = qk
            out, = cur.output("Out")
            nxt = sole_consumer(cur, out)
            scale_op = None
            if nxt is not None and nxt.type == "scale":
                if nxt.attr("bias", 0.0) != 0.0:
                    continue
                scale_op = nxt
                scale *= nxt.attr("scale", 1.0)
                cur, out = nxt, nxt.output("Out")[0]
                nxt = sole_consumer(cur, out)
            if nxt is None or nxt.type != "softmax" or \
                    nxt.attr("axis", -1) not in (-1, 3):
                continue  # fused attention softmaxes the LAST axis
            sm = nxt
            cur, out = sm, sm.output("Out")[0]
            nxt = sole_consumer(cur, out)
            dropout_rate = 0.0
            drop = None
            if nxt is not None and nxt.type == "dropout":
                if nxt.attr("dropout_implementation",
                            "downgrade_in_infer") != "upscale_in_train":
                    continue  # infer-mode scaling changes semantics
                drop = nxt
                dropout_rate = (0.0 if drop.attr("is_test", False)
                                else drop.attr("dropout_prob", 0.5))
                cur, out = drop, drop.output("Out")[0]
                nxt = sole_consumer(cur, out)
            if nxt is None or nxt.type != "matmul" or \
                    nxt.attr("transpose_X", False) or \
                    nxt.attr("transpose_Y", False) or \
                    nxt.attr("alpha", 1.0) != 1.0 or \
                    nxt.input("X")[0] != out:
                continue
            pv = nxt
            final_out = pv.output("Out")[0]
            ops_to_remove = [op for op in (qk, scale_op, sm, drop, pv)
                             if op is not None]
            idx = graph.block.ops.index(qk)
            for dead in ops_to_remove:
                graph.remove_op(dead)
            graph.block.insert_op(
                idx, "attention",
                {"Q": qk.input("X"), "K": qk.input("Y"),
                 "V": pv.input("Y")},
                {"Out": [final_out]},
                {"scale": float(scale), "causal": False,
                 "dropout_rate": float(dropout_rate),
                 "layout": "bhtd"})
            i = idx + 1


@register_pass("identity_elimination_pass")
class IdentityEliminationPass(Pass):
    """Drop no-op ops: scale(scale=1, bias=0), cast to the same dtype,
    chained assign (reference: the simplification family of
    inference passes, e.g. identity_scale_op_clean_pass.cc)."""

    def apply_impl(self, graph: Graph, scope):
        protected = graph.attrs.get("protected", set())
        for op in list(graph.block.ops):
            out_name = None
            if op.type == "scale" and op.attr("scale", 1.0) == 1.0 \
                    and op.attr("bias", 0.0) == 0.0:
                out_name, = op.output("Out")
            elif op.type == "cast":
                src = graph.block._find_var_recursive(op.input("X")[0])
                if src is not None and src.dtype is not None and \
                        op.attr("out_dtype") in (src.dtype,
                                                 getattr(src.dtype,
                                                         "value", None)):
                    out_name, = op.output("Out")
            elif op.type == "assign":
                # collapse assigns only into pure temps (a persistable
                # target is a state write-back the executor threads)
                out_name, = op.output("Out")
                var = graph.block._find_var_recursive(out_name)
                if var is not None and var.persistable:
                    out_name = None
            if out_name is None or out_name in protected:
                continue
            x, = op.input("X")
            # rewiring readers of out_name to x is only sound if
            # NEITHER name is redefined later (an in-place write to x
            # would leak the new value into pre-write readers; a
            # rewrite of out_name would double-apply)
            idx = graph.block.ops.index(op)
            later_writes = {n for later in graph.block.ops[idx + 1:]
                            for n in later.output_arg_names}
            if x in later_writes or out_name in later_writes:
                continue
            graph.replace_input_everywhere(out_name, x, after=op)
            graph.remove_op(op)


@register_pass("conv_relu_fuse_pass")
class ConvReluFusePass(Pass):
    """conv2d [+ per-channel elementwise_add bias] + relu ->
    conv2d_fusion(activation=relu) (reference
    ir/conv_relu_mkldnn_fuse_pass.cc / conv_bias_mkldnn_fuse_pass.cc;
    here the target is the registered conv2d_fusion op,
    ops/extra_ops3 parity family). XLA fuses these anyway -- the pass
    keeps the program-level rewrite capability the reference's
    inference pipeline exposes."""

    def apply_impl(self, graph: Graph, scope):
        protected = graph.attrs.get("protected", set())
        for relu in list(graph.block.ops):
            if relu.type != "relu":
                continue
            prev = graph.producer(relu, "X")
            add = None
            if (prev is not None and prev.type == "elementwise_add"
                    and prev.attr("axis", -1) == 1):
                # only a per-channel (1-D, length C) Y is a conv bias;
                # higher-rank Y uses fluid left-aligned broadcast the
                # fused kernel's (1,C,1,1) reshape would misapply
                y_var = graph.block._find_var_recursive(
                    prev.input("Y")[0])
                if y_var is not None and y_var.shape is not None \
                        and len(y_var.shape) == 1:
                    add = prev
                    prev = graph.producer(add, "X")
            if prev is None or prev.type != "conv2d":
                continue
            conv_out, = prev.output("Output")
            nxt = add if add is not None else relu
            if [c is nxt for c in graph.consumers(prev, conv_out)] \
                    != [True]:
                continue
            mid = add.output("Out")[0] if add is not None else conv_out
            if add is not None:
                if [c is relu for c in graph.consumers(add, mid)] \
                        != [True]:
                    continue
                if mid in protected:
                    continue
            if conv_out in protected:
                continue
            relu_out, = relu.output("Out")
            inputs = {"Input": prev.input("Input"),
                      "Filter": prev.input("Filter")}
            if add is not None:
                inputs["Bias"] = add.input("Y")
            idx = graph.block.ops.index(prev)
            graph.remove_op(prev)
            if add is not None:
                graph.remove_op(add)
            graph.remove_op(relu)
            graph.block.insert_op(
                idx, "conv2d_fusion", inputs, {"Output": [relu_out]},
                {**prev.attrs, "activation": "relu"})


@register_pass("conv_eltwiseadd_fuse_pass")
class ConvEltwiseAddFusePass(Pass):
    """conv2d + same-shape elementwise_add (residual) ->
    conv2d_fusion(ResidualData) (reference
    ir/conv_elementwise_add_fuse_pass.cc)."""

    def apply_impl(self, graph: Graph, scope):
        protected = graph.attrs.get("protected", set())
        for add in list(graph.block.ops):
            if add.type != "elementwise_add":
                continue
            if add.attr("axis", -1) not in (-1, 0):
                continue
            conv = graph.producer(add, "X")
            if conv is None or conv.type != "conv2d":
                continue
            # residual fusion is elementwise: Y must be full-rank
            # NCHW (fluid left-aligned broadcast of a lower-rank Y
            # is NOT what the fused kernel's plain add computes)
            y_var = graph.block._find_var_recursive(add.input("Y")[0])
            if y_var is None or y_var.shape is None \
                    or len(y_var.shape) != 4:
                continue
            conv_out, = conv.output("Output")
            if conv_out in protected:
                continue
            if [c is add for c in graph.consumers(conv, conv_out)] \
                    != [True]:
                continue
            add_out, = add.output("Out")
            idx = graph.block.ops.index(conv)
            graph.remove_op(conv)
            graph.remove_op(add)
            graph.block.insert_op(
                idx, "conv2d_fusion",
                {"Input": conv.input("Input"),
                 "Filter": conv.input("Filter"),
                 "ResidualData": add.input("Y")},
                {"Output": [add_out]},
                {**conv.attrs, "activation": "identity"})


class _FuseOptimizerBase(Pass):
    """Fuse N per-param optimizer ops into ONE update over coalesced
    buffers (reference details/fuse_optimizer_op_pass.cc +
    fuse_sgd_op_pass.cc / fuse_adam_op_pass.cc). Plan per group of
    fusable ops (same type, same attrs, same LearningRate):

        alloc_continuous_space per slot (Param, Grad, moments...) ->
        one optimizer op on the fused 1-D buffers ->
        slice+reshape the updated fused param (and moments) back to
        the ORIGINAL names, so the executor's state write-back is
        untouched.

    On TPU the speedup motive is gone (XLA fuses the elementwise
    updates anyway); the pass keeps the reference's program-level
    rewrite capability, and the fused form is what
    fuse_all_reduce-style distributed rewrites key on."""

    op_type = None
    state_slots = ()     # per-param state to coalesce alongside Param
    scalar_slots = ()    # per-param [1]-shaped inputs equal across the
    # group (beta pows) -- the fused op reuses the first op's var

    def _fusable(self, ops):
        groups = {}
        for op in ops:
            if op.type != self.op_type:
                continue
            key = (tuple(sorted(op.attrs.items())),
                   tuple(op.input("LearningRate")))
            groups.setdefault(key, []).append(op)
        return [g for g in groups.values() if len(g) > 1]

    def apply_impl(self, graph: Graph, scope):
        from . import unique_name

        block = graph.block
        for group in self._fusable(list(block.ops)):
            first = group[0]
            idx = min(block.ops.index(op) for op in group)
            for op in group:
                block.ops.remove(op)
            new_ops = []

            def coalesce(slot):
                names = [op.input(slot)[0] for op in group]
                fused = unique_name.generate(f"fused_{slot.lower()}")
                view_names = [unique_name.generate(f"{n}@VIEW")
                              for n in names]
                block.create_var(name=fused)
                for v in view_names:
                    block.create_var(name=v)
                new_ops.append(Operator(
                    block, "alloc_continuous_space",
                    {"Input": names},
                    {"Output": view_names, "FusedOutput": [fused]}, {}))
                return names, fused

            slots = ("Param", "Grad") + tuple(self.state_slots)
            fused_names = {}
            orig_names = {}
            for slot in slots:
                orig_names[slot], fused_names[slot] = coalesce(slot)

            fused_out = {}
            op_inputs = {s: [fused_names[s]] for s in slots}
            op_inputs["LearningRate"] = first.input("LearningRate")
            for s in self.scalar_slots:
                op_inputs[s] = first.input(s)
            op_outputs = {}
            for s in ("Param",) + tuple(self.state_slots):
                fo = unique_name.generate(f"fused_{s.lower()}_out")
                block.create_var(name=fo)
                fused_out[s] = fo
                op_outputs[s + "Out"] = [fo]
            new_ops.append(Operator(block, self.op_type, op_inputs,
                                    op_outputs, dict(first.attrs)))
            # beta-pow style scalar state keeps advancing via explicit
            # scale ops (reference fuse_adam_op_pass.cc FuseScaleOps);
            # the fused op reads the first op's pows but updates none
            for slot in self.scalar_slots:
                factor = first.attrs.get(
                    {"Beta1Pow": "beta1", "Beta2Pow": "beta2"}.get(
                        slot, ""), None)
                if factor is None:
                    continue
                for op in group:
                    pow_name = op.input(slot)[0]
                    new_ops.append(Operator(
                        block, "scale", {"X": [pow_name]},
                        {"Out": [pow_name]},
                        {"scale": float(factor), "bias": 0.0}))

            # scatter updated fused buffers back to the original vars
            for s in ("Param",) + tuple(self.state_slots):
                off = 0
                for op, orig in zip(group, orig_names[s]):
                    var = block._find_var_recursive(orig)
                    shape = list(var.shape)
                    n = int(np.prod(shape)) if shape else 1
                    flat = unique_name.generate(f"{orig}@FLAT")
                    block.create_var(name=flat)
                    new_ops.append(Operator(
                        block, "slice", {"Input": [fused_out[s]]},
                        {"Out": [flat]},
                        {"axes": [0], "starts": [off],
                         "ends": [off + n]}))
                    new_ops.append(Operator(
                        block, "reshape", {"X": [flat]},
                        {"Out": [orig]}, {"shape": shape}))
                    off += n
            for i, nop in enumerate(new_ops):
                block.ops.insert(idx + i, nop)


@register_pass("fuse_sgd_op_pass")
class FuseSgdOpPass(_FuseOptimizerBase):
    """reference details/fuse_sgd_op_pass.cc."""

    op_type = "sgd"


@register_pass("fuse_adam_op_pass")
class FuseAdamOpPass(_FuseOptimizerBase):
    """reference details/fuse_adam_op_pass.cc. Beta pows are shared
    from the group's first op (they are numerically identical across
    params: same init, same step count -- the reference reaches the
    same state through FuseScaleOps)."""

    op_type = "adam"
    state_slots = ("Moment1", "Moment2")
    scalar_slots = ("Beta1Pow", "Beta2Pow")
