"""Downpour parameter-server package (fleet precursor).

Parity: reference python/paddle/fluid/distributed/ (downpour.py,
node.py, ps_instance.py, helper.py; ps_pb2 protobufs are replaced by
plain dict descs -- SURVEY.md §2.7 "distributed (downpour PS)")."""
from .downpour import DownpourSGD  # noqa: F401
from .helper import EnvRoleHelper, FileSystem  # noqa: F401
from .node import (DownpourServer, DownpourWorker, Server,  # noqa: F401
                   Worker)
from .ps_instance import PaddlePSInstance  # noqa: F401
