"""Downpour server/worker descriptors.

Parity: reference python/paddle/fluid/distributed/node.py -- Server /
Worker / DownpourServer (:35, add_sparse_table :53, add_dense_table
:86) / DownpourWorker (:122). The reference fills PSLib protobufs
(ps_pb2) configuring the Baidu brpc parameter server; the TPU-native
backend is the in-repo PS runtime (transpiler/pserver_runtime.py over
TCP + io_callback), so the descs here are plain dicts with the same
logical fields (table ids, accessor params, slot var names)."""
from __future__ import annotations


class Server:
    """A server description base (reference node.py:17)."""

    def __init__(self):
        pass


class Worker:
    """A worker description base (reference node.py:26)."""

    def __init__(self):
        pass


class DownpourServer(Server):
    """Generates the server-side table plan (reference node.py:35)."""

    def __init__(self):
        super().__init__()
        self._desc = {
            "service": {
                # reference wires DownpourBrpcPsServer/Client; ours is
                # the pserver_runtime TCP transport
                "server_class": "PTpuPsServer",
                "client_class": "PTpuPsClient",
                "service_class": "PTpuPsService",
            },
            "downpour_table_params": [],
        }

    def add_sparse_table(self, table_id, learning_rate,
                         slot_key_vars, slot_value_vars):
        self._desc["downpour_table_params"].append({
            "table_id": table_id,
            "table_class": "DownpourSparseTable",
            "type": "PS_SPARSE_TABLE",
            "accessor": {
                "accessor_class": "DownpourFeatureValueAccessor",
                "learning_rate": learning_rate,
            },
            "slot_key_vars": [v.name for v in slot_key_vars],
            "slot_value_vars": [v.name for v in slot_value_vars],
        })

    def add_dense_table(self, table_id, learning_rate, param_vars,
                        grad_vars):
        self._desc["downpour_table_params"].append({
            "table_id": table_id,
            "table_class": "DownpourDenseTable",
            "type": "PS_DENSE_TABLE",
            "accessor": {
                "accessor_class": "DownpourDenseValueAccessor",
                "learning_rate": learning_rate,
            },
            "dense_param_vars": [v.name for v in param_vars],
            "dense_grad_vars": [g.name for g in grad_vars],
        })

    def get_desc(self):
        return self._desc


class DownpourWorker(Worker):
    """Generates the worker-side pull/push plan (reference
    node.py:122). `window` is the async communication window (how many
    local steps between pushes)."""

    def __init__(self, window=1):
        super().__init__()
        self.window = window
        self._desc = {"window": window, "sparse_tables": [],
                      "dense_tables": []}

    def add_sparse_table(self, table_id, learning_rate,
                         slot_key_vars, slot_value_vars):
        self._desc["sparse_tables"].append({
            "table_id": table_id,
            "learning_rate": learning_rate,
            "slot_key": [v.name for v in slot_key_vars],
            "slot_value": [v.name for v in slot_value_vars],
            "slot_gradient": [v.name + "@GRAD"
                              for v in slot_value_vars],
        })

    def add_dense_table(self, table_id, learning_rate, param_vars,
                        grad_vars):
        self._desc["dense_tables"].append({
            "table_id": table_id,
            "learning_rate": learning_rate,
            "dense_variable_name": [v.name for v in param_vars],
            "dense_gradient_variable_name":
                [g.name for g in grad_vars],
        })

    def get_desc(self):
        return self._desc
