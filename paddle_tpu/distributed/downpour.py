"""DownpourSGD: distributed optimizer for the async PS (CTR) path.

Parity: reference python/paddle/fluid/distributed/downpour.py:24 --
minimize (:47) appends backward, finds the distributed lookup table,
registers one sparse table (the embedding) + one dense table (all
other params) on server and worker descs, and returns
[ps_param, worker_skipped_ops] where the worker must skip the
lookup_table forward/backward ops (the PS serves them via prefetch).

TPU-native: descs are plain dicts (node.py) aimed at the in-repo
pserver runtime; the actual serving path is the distributed-lookup
prefetch rewrite in transpiler/distribute_transpiler.py + ops/dist_ops
(VERDICT row 17), so DownpourSGD is the driver-facing planner that the
AsyncExecutor/downpour flow expects."""
from __future__ import annotations

from ..backward import append_backward
from ..distribute_lookup_table import (
    find_distributed_lookup_table,
    find_distributed_lookup_table_inputs,
    find_distributed_lookup_table_outputs)
from .node import DownpourServer, DownpourWorker


class DownpourSGD:
    """Downpour SGD (Large Scale Distributed Deep Networks, Dean et
    al. 2012): workers pull params, push grads asynchronously with a
    communication `window`."""

    def __init__(self, learning_rate=0.001, window=1):
        self.learning_rate_ = learning_rate
        self.window_ = window
        self.type = "downpour"

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """Append backward + build the PS plan.

        Returns [ps_param, worker_skipped_ops]: ps_param holds
        "server_param"/"trainer_param" descs; worker_skipped_ops are
        op types the worker executor must skip because the parameter
        server owns them (the sparse lookup)."""
        params_grads = sorted(
            append_backward(loss, parameter_list, no_grad_set),
            key=lambda x: x[0].name)
        program = loss.block.program
        table_name = find_distributed_lookup_table(program)
        prefetch_slots = []
        prefetch_slots_emb = []
        if table_name is not None:
            prefetch_slots = find_distributed_lookup_table_inputs(
                program, table_name)
            prefetch_slots_emb = find_distributed_lookup_table_outputs(
                program, table_name)

        server = DownpourServer()
        worker = DownpourWorker(self.window_)
        sparse_table_index = 0
        dense_table_index = 1
        params = [p for p, _ in params_grads
                  if p.name != table_name]
        grads = [g for p, g in params_grads if p.name != table_name]
        server.add_sparse_table(sparse_table_index,
                                self.learning_rate_,
                                prefetch_slots, prefetch_slots_emb)
        server.add_dense_table(dense_table_index, self.learning_rate_,
                               params, grads)
        worker.add_sparse_table(sparse_table_index,
                                self.learning_rate_,
                                prefetch_slots, prefetch_slots_emb)
        worker.add_dense_table(dense_table_index, self.learning_rate_,
                               params, grads)

        worker_skipped_ops = ["lookup_table", "lookup_table_grad"]
        ps_param = {
            "server_param": server.get_desc(),
            "trainer_param": {**worker.get_desc(),
                              "skip_op": list(worker_skipped_ops)},
        }
        return [ps_param, worker_skipped_ops]
