"""Role/communicator helpers for the downpour PS package.

Parity: reference python/paddle/fluid/distributed/helper.py --
FileSystem (:17, hadoop client desc for AsyncExecutor) and MPIHelper
(:56, mpi4py wrapper). TPU-native: roles come from the PADDLE_* env
contract (the same one test_dist_base-style launchers set) with
jax.distributed as the optional barrier backend -- there is no MPI on
TPU pods (SURVEY.md §2.4: coordination service replaces the gRPC/MPI
bootstrap)."""
from __future__ import annotations

import os
import socket


class FileSystem:
    """Hadoop/AFS client description for dataset IO (API parity; the
    TPU build reads local/recordio files, so this is metadata only)."""

    def __init__(self, fs_type="afs", uri="afs://xx", user=None,
                 passwd=None, hadoop_bin=""):
        if user is None or passwd is None or hadoop_bin is None:
            raise ValueError("FileSystem needs user/passwd/hadoop_bin")
        self.fs_client = {
            "fs_type": fs_type, "uri": uri, "user": user,
            "passwd": passwd, "hadoop_bin": hadoop_bin,
        }

    def get_desc(self):
        return self.fs_client


class EnvRoleHelper:
    """get_rank/get_size/barrier over env vars (MPIHelper parity).

    Rank layout follows the reference's mpi world: all processes in
    one world, even ranks = workers, odd = servers when
    server_worker_mode=1 (see ps_instance.py)."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_RANK", os.environ.get(
            "PADDLE_TRAINER_ID", "0")))
        self._size = int(os.environ.get("PADDLE_WORLD_SIZE", os.environ.get(
            "PADDLE_TRAINERS_NUM", "1")))

    def get_rank(self):
        return self._rank

    def get_size(self):
        return self._size

    def get_ip(self):
        return socket.gethostbyname(socket.gethostname())

    def get_hostname(self):
        return socket.gethostname()

    def barrier(self):
        """Cross-process barrier: jax.distributed when running
        multi-process, a no-op single-process. A barrier failure in
        the multi-process case propagates -- silently skipping it
        would let callers race past servers that are not up yet."""
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("downpour_barrier")

    def finalize(self):
        pass
