"""PaddlePSInstance: server/worker role bookkeeping for downpour.

Parity: reference python/paddle/fluid/distributed/ps_instance.py
(:17) -- nodetype constants IDLE=-1 WORKER=1 SERVER=0 (:38), mode-0 =
first half workers / second half servers, mode-1 = alternating by rank
parity within a node (_set_nodetype :43-60). The reference's mode-0
index accessors are typo-broken (`self.server_num` / `self.rank_id`
don't exist, ps_instance.py:75,84); the evident intent -- zero-based
indices within each role group -- is implemented here. The reference
runs on MPI; here ranks come from the PADDLE_* env contract
(helper.EnvRoleHelper), matching how the in-repo dist tests launch
subprocesses (tests/test_dist_multiprocess.py)."""
from __future__ import annotations

from .helper import EnvRoleHelper


class PaddlePSInstance:
    def __init__(self, server_worker_mode=1, proc_per_node=2,
                 helper=None):
        self.dh = helper or EnvRoleHelper()
        self._rankid = self.dh.get_rank()
        self._server_worker_mode = server_worker_mode
        self._proc_per_node = proc_per_node
        self._nodes = max(self.dh.get_size() // proc_per_node, 1)
        total = self._nodes * proc_per_node
        self._worker_num = total // 2
        self._server_num = total // 2
        self._ip = 0
        self._set_nodetype()
        self._split_comm()

    def _set_nodetype(self):
        # IDLE=-1, WORKER=1, SERVER=0 (reference ps_instance.py:38)
        total = self._worker_num + self._server_num
        if self._server_worker_mode == 0:
            # first half of ranks are workers, second half servers
            if self._rankid < self._worker_num:
                self._node_type = 1
            elif self._rankid < total:
                self._node_type = 0
            else:
                self._node_type = -1
        elif self._server_worker_mode == 1:
            # alternating within each node: even local rank = server
            if self._rankid < total:
                local = self._rankid % self._proc_per_node
                self._node_type = 0 if local % 2 == 0 else 1
            else:
                self._node_type = -1
        else:
            self._node_type = -1

    def _split_comm(self):
        # MPI Comm.Split analogue: zero-based index within this
        # process's role group (used for shard addressing)
        self._group_index = (self.get_worker_index() if self.is_worker()
                             else self.get_server_index()
                             if self.is_server() else -1)

    def get_worker_index(self):
        if self._server_worker_mode == 0:
            return self._rankid  # workers occupy ranks [0, worker_num)
        return self._rankid // self._proc_per_node

    def get_server_index(self):
        if self._server_worker_mode == 0:
            return self._rankid - self._worker_num
        return self._rankid // self._proc_per_node

    def is_worker(self):
        return self._node_type == 1

    def is_server(self):
        return self._node_type == 0

    def is_first_worker(self):
        return self.is_worker() and self.get_worker_index() == 0

    def set_ip(self, ip):
        self._ip = ip

    def gather_ips(self):
        # single-host fallback: everyone shares this host's ip
        self._ips = [self.dh.get_ip()] * self.dh.get_size()
        return self._ips

    def get_node_cnt(self):
        return self._nodes

    def barrier_all(self):
        self.dh.barrier()

    def barrier_worker(self):
        if self.is_worker():
            self.dh.barrier()

    def finalize(self):
        self.dh.finalize()
