"""Convenience network compositions (reference python/paddle/fluid/nets.py:
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention).
"""
from __future__ import annotations

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group",
           "sequence_conv_pool", "glu", "scaled_dot_product_attention"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act)
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    tmp = input
    if not isinstance(conv_padding, (list, tuple)):
        conv_padding = [conv_padding] * len(conv_num_filter)
    if not isinstance(conv_with_batchnorm, (list, tuple)):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if not isinstance(conv_batchnorm_drop_rate, (list, tuple)):
        conv_batchnorm_drop_rate = [conv_batchnorm_drop_rate] * \
            len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        local_act = conv_act if not conv_with_batchnorm[i] else None
        tmp = layers.conv2d(
            input=tmp, num_filters=nf, filter_size=conv_filter_size,
            padding=conv_padding[i], param_attr=param_attr,
            act=local_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = layers.dropout(tmp, conv_batchnorm_drop_rate[i])
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None):
    conv_out = layers.sequence_conv(
        input=input, num_filters=num_filters, filter_size=filter_size,
        param_attr=param_attr, bias_attr=bias_attr, act=act)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """reference nets.py scaled_dot_product_attention. The attention op
    itself is the Pallas flash-attention kernel when shapes allow
    (ops/pallas/attention.py), else the jnp composition."""
    d = queries.shape[-1]
    head_dim = d // num_heads

    def _split_heads(x):
        # [B, T, D] -> [B, H, T, D/H]
        b, t = x.shape[0], x.shape[1]
        y = layers.reshape(x, [0, 0, num_heads, head_dim])
        return layers.transpose(y, [0, 2, 1, 3])

    q, k, v = map(_split_heads, (queries, keys, values))
    scaled = layers.scale(q, scale=head_dim ** -0.5)
    logits = layers.matmul(scaled, k, transpose_y=True)
    weights = layers.softmax(logits)
    if dropout_rate:
        weights = layers.dropout(
            weights, dropout_rate,
            dropout_implementation="upscale_in_train")
    ctx = layers.matmul(weights, v)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    return layers.reshape(ctx, [0, 0, d])
