"""Program-level reverse autodiff: append_backward / gradients.

Parity target: reference python/paddle/fluid/backward.py:394
(append_backward), :135 (_addup_repetitive_outputs_), :204
(_remove_no_grad_branch_), :613 (calc_gradient).

Walks the forward op list in reverse, asks each op's grad maker
(core/registry.py -- usually the generic jax.vjp-derived maker) for grad
op descs, accumulates duplicate gradients with `sum` ops (a forward var
consumed by N ops receives N partial grads), and substitutes @EMPTY@ for
output-grads never reached by backprop (the reference inserts
fill_zeros_like ops instead; our vjp kernels synthesize zeros lazily,
which XLA folds away).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from .core.program import (GRAD_SUFFIX, Operator, Variable, grad_var_name)
from .core.registry import EMPTY_VAR, get_op_info, make_grad_ops
from . import unique_name

OP_ROLE_KEY = "op_role"


def _find_op_path(block, loss_name: str, stop_names: Set[str]):
    """Ops that (transitively) produce the loss (reference
    backward.py:573 _find_op_path_)."""
    needed = {loss_name}
    path = []
    for op in reversed(block.ops):
        outs = set(op.output_arg_names)
        if outs & needed:
            path.append(op)
            for n in op.input_arg_names:
                if n not in stop_names and n != EMPTY_VAR:
                    needed.add(n)
    path.reverse()
    return path


def _collect_no_grad(block, extra=None) -> Set[str]:
    no_grad = set(extra or ())
    for var in block.vars.values():
        # data vars default stop_gradient=True via layers.data(); an
        # explicit stop_gradient=False on a data var lets gradients
        # flow to it (fluid semantics — e.g. adversarial-example or
        # detection-loss grad checks)
        if var.stop_gradient:
            no_grad.add(var.name)
        if var.dtype is not None and var.dtype.value.startswith(
                ("int", "uint", "bool")):
            no_grad.add(var.name)
    return no_grad


def _ensure_grad_var(block, fwd_name: str, grad_name: str):
    if grad_name in block.vars:
        return block.vars[grad_name]
    fwd = block._find_var_recursive(fwd_name)
    return block.create_var(
        name=grad_name,
        shape=fwd.shape if fwd is not None else None,
        dtype=fwd.dtype if fwd is not None else None,
        persistable=False)


def _recompute_plan(block, op_path, checkpoints, loss_name):
    """Segment recompute (activation checkpointing): forward vars NOT
    in the checkpoint set are re-produced inside the backward region
    instead of being kept live from forward to backward.

    Parity: the reference line carries this as
    multi_batch-era RecomputeOptimizer /
    _append_backward_ops_with_checkpoints_ (post-v1.3 fluid); on TPU
    it is THE lever for HBM-bound configs (PERF.md: transformer
    batch-256 OOMs on 16 GB without it). Returns
    (segments, saved_names): segments in forward order, each a list of
    ops; every var produced inside a segment and not `saved` gets a
    per-segment @RECOMP clone emitted just before that segment's grad
    ops, so XLA's liveness sees checkpoint-sized residuals only.
    """
    ckpt = {c.name if hasattr(c, "name") else c for c in checkpoints}
    saved = set(ckpt)
    saved.add(loss_name)
    for var in block.vars.values():
        # params/persistables and feeds are always resident
        if var.persistable or var.is_data:
            saved.add(var.name)
    segments = []
    cur = []
    for op in op_path:
        cur.append(op)
        if any(o in ckpt for o in op.output_arg_names):
            segments.append(cur)
            cur = []
    if cur:
        segments.append(cur)
    # a non-saved var consumed OUTSIDE its producing segment (a skip
    # connection bypassing a checkpoint) stays live anyway -- treat it
    # as saved so its consumers read the original rather than chaining
    # recomputes across segments
    producer_seg = {}
    for i, seg in enumerate(segments):
        for op in seg:
            for o in op.output_arg_names:
                producer_seg.setdefault(o, i)
    for i, seg in enumerate(segments):
        for op in seg:
            for n in op.input_arg_names:
                ps = producer_seg.get(n)
                if ps is not None and ps != i:
                    saved.add(n)
    return segments, saved


def _emit_recompute(block, segment, saved, seg_idx):
    """Clone `segment`'s ops re-deriving its non-saved activations from
    saved vars; returns {orig_name: recomputed_name}.

    Every clone input coming from OUTSIDE the recompute region is
    routed through an optimization_barrier op: without it the clones
    are byte-identical HLO to the forward ops and XLA's CSE merges
    them back, silently undoing the memory saving (the same reason
    jax.remat wraps rematerialized computations in barriers)."""
    remap = {}
    barriered = {}

    def _bar(name):
        if name in barriered:
            return barriered[name]
        bname = unique_name.generate(f"{name}@BAR{seg_idx}")
        bop = Operator(block, "optimization_barrier",
                       {"X": [name]}, {"Out": [bname]},
                       {OP_ROLE_KEY: "backward"})
        block.ops.append(bop)
        _ensure_grad_var(block, name, bname)
        barriered[name] = bname
        return bname

    for op in segment:
        out_renames = {}
        for n in op.output_arg_names:
            if n in saved:
                continue
            out_renames[n] = unique_name.generate(
                f"{n}@RECOMP{seg_idx}")
        if not out_renames:
            continue
        def _src(n):
            # NOTE: must stay lazy -- remap.get(n, _bar(n)) would emit
            # a dead barrier (pinning the original activation) for
            # every already-remapped name
            if n in remap:
                return remap[n]
            return _bar(n) if n != EMPTY_VAR else n

        clone = Operator(
            block, op.type,
            {slot: [_src(n) for n in names]
             for slot, names in op.inputs.items()},
            {slot: [out_renames.get(n, n) for n in names]
             for slot, names in op.outputs.items()},
            dict(op.attrs))
        # same structural uid => sampling ops (dropout) re-toss the
        # IDENTICAL noise in the recompute, keeping fwd/bwd consistent
        clone._uid = op._uid
        clone.attrs[OP_ROLE_KEY] = "backward"
        block.ops.append(clone)
        for orig, renamed in out_renames.items():
            _ensure_grad_var(block, orig, renamed)
            remap[orig] = renamed
    return remap


def append_backward(loss: Variable, parameter_list=None,
                    no_grad_set=None, callbacks=None,
                    checkpoints=None):
    """Append grad ops for `loss`; returns [(param, grad_var)] pairs.

    `checkpoints`: optional list of forward vars (or names) to keep;
    activations between consecutive checkpoints are recomputed in the
    backward region (see _recompute_plan)."""
    block = loss.block
    program = block.program
    no_grad = _collect_no_grad(block, no_grad_set)

    op_path = _find_op_path(block, loss.name, no_grad)

    # vars whose grads the backward pass will actually produce
    grads_wanted: Set[str] = set()
    for op in op_path:
        for n in op.input_arg_names:
            if n not in no_grad:
                grads_wanted.add(n)
        for n in op.output_arg_names:
            grads_wanted.add(n)

    # seed: d loss / d loss = 1
    loss_grad = grad_var_name(loss.name)
    _ensure_grad_var(block, loss.name, loss_grad)
    seed_op = Operator(
        block, "fill_any_like", {"X": [loss.name]}, {"Out": [loss_grad]},
        {"value": 1.0, OP_ROLE_KEY: "backward"})
    block.ops.append(seed_op)

    produced: Set[str] = {loss_grad}

    if checkpoints:
        segments, saved = _recompute_plan(block, op_path, checkpoints,
                                          loss.name)
    else:
        segments, saved = [op_path], None

    for seg_idx in range(len(segments) - 1, -1, -1):
        segment = segments[seg_idx]
        remap = {}
        # the FINAL segment (ops after the last checkpoint, usually
        # the loss head) backs up immediately after forward -- its
        # activations are live at that point anyway, so recomputing
        # them burns FLOPs for zero liveness win (the reference's
        # checkpointing skips the tail the same way)
        # NOTE the tail segment (ops after the last checkpoint, i.e.
        # the loss head) IS recomputed: intuition says its grads run
        # right after forward so there is nothing to free, but on
        # transformer-base the bf16 [B,T,V] logits are 2.1 GB and the
        # TPU compiler's measured temp drops 12.57 -> 10.47 GB with
        # the tail recomputed (XLA schedules the fused dW/adam chain
        # late enough that the original logits otherwise stay live)
        if saved is not None:
            remap = _emit_recompute(block, segment, saved, seg_idx)
        _backward_over(segment, remap, block, no_grad, produced)

    program._version += 1

    # assemble (param, grad) list
    if parameter_list is not None:
        params = []
        for p in parameter_list:
            params.append(p if isinstance(p, Variable)
                          else program.global_block.var(p))
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    param_grads = []
    for p in params:
        g = grad_var_name(p.name)
        if g in produced:
            param_grads.append((p, block.vars[g]))
    return param_grads


def _backward_over(ops, remap, block, no_grad, produced):
    """Emit grad ops for `ops` reversed; `remap` redirects forward-
    activation reads to recomputed clones (empty when not
    checkpointing)."""
    for op in reversed(ops):
        grad_ops = make_grad_ops(op, no_grad_set=no_grad)
        for gop in grad_ops:
            gop.attrs.setdefault(OP_ROLE_KEY, "backward")
            for slot, names in gop.inputs.items():
                if slot.endswith(GRAD_SUFFIX):
                    # rewrite grad inputs never produced -> @EMPTY@
                    gop.inputs[slot] = [
                        n if n in produced else EMPTY_VAR
                        for n in names]
                elif remap:
                    # forward-activation reads go to the recompute
                    gop.inputs[slot] = [remap.get(n, n) for n in names]
            # handle duplicate grad production: accumulate with sum
            renames = []
            for slot, names in gop.outputs.items():
                new_names = []
                for n in names:
                    if n in produced:
                        tmp = unique_name.generate(n + "@RENAME")
                        renames.append((n, tmp))
                        new_names.append(tmp)
                    else:
                        new_names.append(n)
                gop.outputs[slot] = new_names
            block.ops.append(gop)
            for slot, names in gop.outputs.items():
                fwd_slot = slot[:-len(GRAD_SUFFIX)]
                fwd_names = (op.inputs.get(fwd_slot, [])
                             if gop.type.endswith("_grad") else [])
                for i, n in enumerate(names):
                    src = fwd_names[i] if i < len(fwd_names) else None
                    _ensure_grad_var(block, src or n, n)
                    produced.add(n)
            for orig, tmp in renames:
                sum_op = Operator(
                    block, "sum", {"X": [orig, tmp]}, {"Out": [orig]},
                    {OP_ROLE_KEY: "backward"})
                block.ops.append(sum_op)
                produced.add(orig)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference backward.py:613 calc_gradient-era API."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    assert len(targets) == 1, "gradients: single target supported"
    loss = targets[0]
    block = loss.block
    pairs = append_backward(loss, no_grad_set=no_grad_set)
    grads = []
    for v in inputs:
        g = grad_var_name(v.name)
        grads.append(block.vars.get(g))
    return grads
