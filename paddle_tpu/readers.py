"""Reader composition decorators + batching.

Parity: reference python/paddle/reader/decorator.py (map_readers,
shuffle, buffered, compose, chain, firstn, xmap_readers, cache,
multiprocess_reader) and python/paddle/batch.py (batch). A "reader" is a
zero-arg callable returning an iterator of samples.
"""
from __future__ import annotations

import itertools
import queue
import random
import threading
from typing import Callable, Iterable, List

__all__ = ["map_readers", "shuffle", "buffered", "compose", "chain",
           "firstn", "xmap_readers", "cache", "multiprocess_reader",
           "batch"]


def map_readers(func: Callable, *readers):
    """Yield func applied across items of several readers in lockstep."""

    def reader():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)

    return reader


def shuffle(reader, buf_size: int, seed=None):
    """Buffered shuffle (reference decorator.py shuffle)."""

    def data_reader():
        rng = random.Random(seed)
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            rng.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def buffered(reader, size: int):
    """Background-thread prefetch buffer (reference buffered)."""

    class _End:
        pass

    def data_reader():
        q: queue.Queue = queue.Queue(maxsize=size)
        exc = []

        def fill():
            try:
                for d in reader():
                    q.put(d)
            except BaseException as e:  # propagate to consumer
                exc.append(e)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                if exc:
                    raise exc[0]
                return
            yield e

    return data_reader


def compose(*readers, check_alignment=True):
    """Zip several readers into flat tuples (reference compose)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        its = [r() for r in readers]
        if check_alignment:
            # pull manually (not zip) so a reader that is exactly one
            # item longer than another is still detected as ragged
            while True:
                items = []
                stopped = 0
                for it in its:
                    try:
                        items.append(next(it))
                    except StopIteration:
                        stopped += 1
                if stopped == len(its):
                    return
                if stopped:
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in itertools.zip_longest(*its):
                yield sum((make_tuple(i) for i in items if i is not None),
                          ())

    return reader


def chain(*readers):
    def reader():
        for r in readers:
            for e in r():
                yield e

    return reader


def firstn(reader, n: int):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                return
            yield item

    return data_reader


def cache(reader):
    """Materialize once; replay from memory afterwards. A partially
    consumed first pass discards its partial cache and refills on the
    next call (so early `break`/`firstn` can't corrupt the cache)."""
    all_data: List = []
    filled = [False]

    def data_reader():
        if not filled[0]:
            all_data.clear()
            for d in reader():
                all_data.append(d)
                yield d
            filled[0] = True
        else:
            for d in all_data:
                yield d

    return data_reader


def xmap_readers(mapper, reader, process_num: int, buffer_size: int,
                 order=False):
    """Parallel map over a reader with worker threads (reference
    xmap_readers; threads not processes — mappers are numpy-bound)."""

    class _End:
        pass

    def data_reader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        errors: List[BaseException] = []

        def feed():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
            except BaseException as e:
                errors.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(_End)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is _End:
                        return
                    i, d = item
                    out_q.put((i, mapper(d)))
            except BaseException as e:
                errors.append(e)
            finally:
                out_q.put(_End)

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if order:
            pending = {}
            next_i = 0
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                pending[item[0]] = item[1]
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
            if not errors:
                for i in sorted(pending):
                    yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                yield item[1]
        if errors:
            raise errors[0]

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave several readers via worker threads (API parity with the
    reference's multiprocess_reader; thread-backed here since samples are
    numpy arrays and the GIL is released in numpy)."""

    class _End:
        pass

    def data_reader():
        q: queue.Queue = queue.Queue(queue_size)
        errors: List[BaseException] = []

        def work(r):
            try:
                for d in r():
                    q.put(d)
            except BaseException as e:
                errors.append(e)
            finally:
                q.put(_End)

        for r in readers:
            threading.Thread(target=work, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            item = q.get()
            if item is _End:
                finished += 1
                continue
            yield item
        if errors:
            raise errors[0]

    return data_reader


def batch(reader, batch_size: int, drop_last=False):
    """Group samples into lists of batch_size (reference batch.py)."""

    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


class ComposeNotAligned(ValueError):
    """reference python/paddle/reader/decorator.py:145 — raised by
    compose(check_alignment=True) on ragged readers."""


class PipeReader:
    """reference python/paddle/reader/decorator.py:460 — stream lines
    from a shell command's stdout (e.g. `hadoop fs -cat`, zcat)."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        if not isinstance(command, str):
            raise TypeError("pipe command must be a string")
        self.command = command
        self.bufsize = bufsize
        if file_type not in ("plain", "gzip"):
            raise TypeError("file_type must be plain or gzip")
        self.file_type = file_type

    def get_line(self, cut_lines=True, line_break="\n"):
        import codecs
        import subprocess

        proc = subprocess.Popen(self.command.split(),
                                stdout=subprocess.PIPE)
        out = proc.stdout
        if self.file_type == "gzip":
            import zlib

            decomp = zlib.decompressobj(32 + zlib.MAX_WBITS)
        # incremental decode: a multibyte char split across bufsize
        # reads must not become U+FFFD garbage
        decoder = codecs.getincrementaldecoder("utf8")("replace")
        remained = ""
        while True:
            buf = out.read(self.bufsize)
            if not buf:
                break
            if self.file_type == "gzip":
                raw = decomp.decompress(buf)
                # concatenated gzip members (hadoop part files,
                # `cat a.gz b.gz`): restart on each member boundary
                # instead of silently dropping the rest
                while decomp.unused_data:
                    rest = decomp.unused_data
                    decomp = zlib.decompressobj(32 + zlib.MAX_WBITS)
                    raw += decomp.decompress(rest)
            else:
                raw = buf
            data = decoder.decode(raw)
            if cut_lines:
                lines = (remained + data).split(line_break)
                remained = lines.pop()
                yield from lines
            else:
                yield data
        remained += decoder.decode(b"", final=True)
        if remained:
            yield remained
        if proc.wait() != 0:
            raise IOError(
                f"pipe command {self.command!r} exited with status "
                f"{proc.returncode}")


class Fake:
    """reference python/paddle/reader/decorator.py:531 — cache the
    first item of a reader and replay it data_num times (speed-test
    harness reader)."""

    def __init__(self):
        self.data = None

    def __call__(self, reader, data_num):
        def fake_reader():
            if self.data is None:
                try:
                    self.data = next(reader())
                except StopIteration:
                    raise ValueError(
                        "Fake needs a non-empty source reader")
            # count locally: a partially-consumed or concurrent
            # iterator must not shorten later passes
            for _ in range(data_num):
                yield self.data

        return fake_reader


__all__.extend(["ComposeNotAligned", "PipeReader", "Fake"])
