"""Sharding rules: param-name patterns -> PartitionSpec.

This is the TPU-native replacement for the reference's multi-device SSA
graph builders (multi_devices_graph_pass.cc: replicate ops per device +
insert collectives per grad) AND its DistributeTranspiler param slicing
(distribute_transpiler.py:69 VarBlock / :1131 _init_splited_vars): instead
of rewriting the program, we annotate where each tensor lives on the mesh
and let XLA GSPMD insert psum/all-gather/reduce-scatter on ICI.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardingRules:
    """Ordered (regex, PartitionSpec) table; first match wins."""

    def __init__(self, rules: List[Tuple[str, P]], default: P = P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default = default

    def spec_for(self, name: str, ndim: int) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                if len(spec) <= ndim:
                    return spec
        return self.default

    def sharding_for(self, mesh: Mesh, name: str, ndim: int):
        return NamedSharding(mesh, self.spec_for(name, ndim))


def default_transformer_rules() -> ShardingRules:
    """Megatron-style TP for the transformer stack built by
    models/transformer.py (fc weights are [in, out]):
      * ffn up-projection + attention qkv projections: shard OUT dim
      * ffn down-projection + attention output proj: shard IN dim
      * embeddings: shard vocab (row) dim
    XLA inserts the psum for the row-sharded matmuls automatically.
    """
    return ShardingRules([
        (r"word_emb", P("tp", None)),
        # fc layers inside transformer blocks: alternate by name counter
        # is fragile; shard the big [512, 2048] up-proj on out dim and
        # [2048, 512] down-proj on in dim by matching shape at apply
        # time via spec_for_shape below.
    ])


def spec_for_param(name: str, shape, rules: Optional[ShardingRules],
                   tp_threshold: int = 1024) -> P:
    """Heuristic TP assignment when no explicit rule matches: shard the
    largest dim of big 2-D weights over 'tp'."""
    if rules is not None:
        spec = rules.spec_for(name, len(shape))
        if spec != P():
            return spec
    if len(shape) == 2 and max(shape) >= tp_threshold:
        if shape[1] >= shape[0]:
            return P(None, "tp")
        return P("tp", None)
    return P()


def shard_state(state: Dict, mesh: Mesh,
                rules: Optional[ShardingRules] = None) -> Dict:
    """Place a scope state-dict on the mesh per rules (params replicated
    across dp, TP-sharded where rules/heuristics say)."""
    out = {}
    for name, val in state.items():
        if val is None:
            out[name] = val
            continue
        shape = getattr(val, "shape", ())
        spec = spec_for_param(name, shape, rules)
        out[name] = jax.device_put(val, NamedSharding(mesh, spec))
    return out


def replicate(value, mesh: Mesh):
    return jax.device_put(value, NamedSharding(mesh, P()))
