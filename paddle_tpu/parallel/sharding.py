"""Sharding rules: param-name patterns -> PartitionSpec.

This is the TPU-native replacement for the reference's multi-device SSA
graph builders (multi_devices_graph_pass.cc: replicate ops per device +
insert collectives per grad) AND its DistributeTranspiler param slicing
(distribute_transpiler.py:69 VarBlock / :1131 _init_splited_vars): instead
of rewriting the program, we annotate where each tensor lives on the mesh
and let XLA GSPMD insert psum/all-gather/reduce-scatter on ICI.
"""
from __future__ import annotations

import re
import warnings
from typing import Dict, List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardingRules:
    """Ordered (regex, PartitionSpec) table; first match wins."""

    def __init__(self, rules: List[Tuple[str, P]], default: P = P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default = default

    def spec_for(self, name: str, ndim: int) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                if len(spec) <= ndim:
                    return spec
        return self.default

    def sharding_for(self, mesh: Mesh, name: str, ndim: int):
        return NamedSharding(mesh, self.spec_for(name, ndim))


def default_transformer_rules() -> ShardingRules:
    """Megatron-style TP for the transformer stack built by
    models/transformer.py (fc weights are [in, out]):
      * ffn up-projection + attention qkv projections: shard OUT dim
      * ffn down-projection + attention output proj: shard IN dim
      * embeddings: shard vocab (row) dim
    XLA inserts the psum for the row-sharded matmuls automatically.
    """
    return ShardingRules([
        (r"word_emb", P("tp", None)),
        # fc layers inside transformer blocks: alternate by name counter
        # is fragile; shard the big [512, 2048] up-proj on out dim and
        # [2048, 512] down-proj on in dim by matching shape at apply
        # time via spec_for_shape below.
    ])


def spec_for_param(name: str, shape, rules: Optional[ShardingRules],
                   tp_threshold: int = 1024) -> P:
    """Heuristic TP assignment when no explicit rule matches: shard the
    largest dim of big 2-D weights over 'tp'. Prefer
    `derive_sharding_rules(program)` — the structural pass — whenever a
    Program is available; this size heuristic is the fallback for bare
    state dicts."""
    if rules is not None:
        spec = rules.spec_for(name, len(shape))
        if spec != P() or isinstance(rules, DerivedRules):
            # a DerivedRules table is exhaustive: replicated means the
            # structural pass DECIDED replicated (e.g. a residual-
            # escaped weight) — the size heuristic must not override it
            return spec
    if len(shape) == 2 and max(shape) >= tp_threshold:
        if shape[1] >= shape[0]:
            return P(None, "tp")
        return P("tp", None)
    return P()


# ---------------------------------------------------------------------------
# Structural TP rules derived from the program graph
# ---------------------------------------------------------------------------
# Ops a column-sharded activation may flow through on its way to the
# paired row-sharded projection without forcing a gather: shape/layout
# ops, elementwise activations, and the fused attention op (a
# head-partitioned attention needs no cross-head communication).
_TP_PASS_OPS = {
    "split", "reshape2", "reshape", "transpose2", "transpose",
    "relu", "gelu", "tanh", "sigmoid", "scale", "dropout",
    "attention", "cast",
}


# optimizer accumulators are named <param>_<acc>_<n> by
# Optimizer._add_accumulator (optimizer.py:74, unique_name suffix);
# only these suffixes may inherit the parent param's spec — a bare
# startswith() would also capture unrelated params whose name merely
# extends another's (e.g. a deliberately-replicated fc_w_scale next
# to a sharded fc_w)
_ACC_SUFFIX = re.compile(
    r"^(velocity|moment[12]?|beta[12]_pow_acc|inf_norm|momentum"
    r"|avg_squared_(?:grad|update)|mean_(?:square|grad)|squared"
    r"|linear|dgc_[uv]|sum_\d+|num_accumulates)_\d+$")


class DerivedRules(ShardingRules):
    """Exact param-name -> PartitionSpec table from the structural
    pass; quacks like ShardingRules for shard_state/spec_for_param.
    The table is EXHAUSTIVE: names not in it (directly or via their
    parent param, see below) are deliberately replicated — no size
    heuristic applies on top."""

    def __init__(self, table: Dict[str, P]):
        self.table = dict(table)
        self.default = P()
        self._keys = sorted(self.table, key=len, reverse=True)

    def spec_for(self, name: str, ndim: int) -> P:
        spec = self.table.get(name)
        if spec is None:
            # param-shaped optimizer accumulators inherit the param's
            # spec so Adam state keeps the TP memory savings. Rank
            # mismatches (e.g. the (1,) beta-pow accumulators) fall
            # through to replicated below.
            for key in self._keys:
                if name.startswith(key + "_") and \
                        _ACC_SUFFIX.match(name[len(key) + 1:]):
                    spec = self.table[key]
                    break
        if spec is None:
            return P()
        return spec if len(spec) <= ndim else P()

    def __repr__(self):
        return f"DerivedRules({self.table})"


def derive_sharding_rules(program) -> DerivedRules:
    """Derive Megatron-style tensor-parallel PartitionSpecs from the
    PROGRAM GRAPH instead of weight sizes (the reference's analogue
    decides placement per-op in multi_devices_graph_pass.cc:40).

    Pattern: for each projection `mul(X, W_a)`, chase its output
    forward through `_TP_PASS_OPS` (+ rank-1 param bias adds). If
    every path lands on another projection `mul(., W_b)` — the FFN
    up/down pair, or qkv -> attention -> out-proj — then W_a is
    column-sharded P(None, 'tp'), its bias P('tp'), W_b row-sharded
    P('tp', None), its bias replicated (the row matmul's partial sums
    are psum'd once by GSPMD). If any path escapes (residual add,
    layer_norm, loss...), W_a stays replicated — a column shard there
    would force a gather per matmul.

    Embeddings (`lookup_table` W) are vocab-row-sharded; a logits head
    (projection onto an embedding-sized vocab feeding
    softmax_with_cross_entropy) is vocab-column-sharded — Megatron's
    parallel vocab loss.
    """
    block = program.global_block

    def persistable(name):
        v = block._find_var_recursive(name)
        return v is not None and v.persistable

    def var_shape(name):
        v = block._find_var_recursive(name)
        return tuple(v.shape) if v is not None and v.shape else ()

    fwd_ops = [op for op in block.ops
               if op.attrs.get("op_role") not in ("backward", "optimize")
               and op.type not in ("feed", "fetch")]
    consumers: Dict[str, list] = {}
    for i, op in enumerate(fwd_ops):
        for names in op.inputs.values():
            for n in names:
                consumers.setdefault(n, []).append(i)

    def is_proj(op):
        if op.type not in ("mul", "matmul"):
            return False
        y = op.inputs.get("Y", [None])[0]
        return y is not None and persistable(y)

    def bias_of(op):
        """The rank-1 param added right onto this projection's out."""
        out = op.outputs["Out"][0]
        for ci in consumers.get(out, []):
            c = fwd_ops[ci]
            if c.type == "elementwise_add":
                y = c.inputs.get("Y", [None])[0]
                if y and persistable(y) and len(var_shape(y)) == 1:
                    return y
        return None

    table: Dict[str, P] = {}
    vocab_sizes = set()
    for op in fwd_ops:
        if op.type == "lookup_table":
            w = op.inputs["W"][0]
            table[w] = P("tp", None)
            vocab_sizes.add(var_shape(w)[0] if var_shape(w) else None)

    def downstream_projs(op):
        """(reached projection op idxs, escaped?) chasing op's Out."""
        reached, escaped = set(), False
        seen = set()
        stack = [op.outputs["Out"][0]]
        while stack:
            var = stack.pop()
            if var in seen:
                continue
            seen.add(var)
            for ci in consumers.get(var, []):
                c = fwd_ops[ci]
                if is_proj(c) and var in c.inputs.get("X", []):
                    reached.add(ci)
                elif c.type == "elementwise_add":
                    y = c.inputs.get("Y", [None])[0]
                    if y and persistable(y) and len(var_shape(y)) == 1:
                        stack.extend(c.outputs["Out"])   # bias add
                    else:
                        escaped = True                   # residual
                elif c.type in _TP_PASS_OPS:
                    for names in c.outputs.values():
                        stack.extend(names)
                else:
                    escaped = True
        return reached, escaped

    for i, op in enumerate(fwd_ops):
        if not is_proj(op):
            continue
        w = op.inputs["Y"][0]
        if w in table:
            continue          # already assigned (e.g. row by a pair)
        shp = var_shape(w)
        if len(shp) != 2:
            continue
        # vocab head: projection onto an embedding vocab feeding the
        # softmax loss
        out = op.outputs["Out"][0]
        outs_cs = [fwd_ops[ci].type for ci in consumers.get(out, [])]
        if shp[1] in vocab_sizes and \
                "softmax_with_cross_entropy" in outs_cs:
            table[w] = P(None, "tp")
            continue
        reached, escaped = downstream_projs(op)
        if escaped or not reached:
            continue
        down_ws = [fwd_ops[ci].inputs["Y"][0] for ci in reached]
        if any(table.get(dw) == P(None, "tp") for dw in down_ws):
            continue          # would chain column->column; stay safe
        table[w] = P(None, "tp")
        b = bias_of(op)
        if b:
            table[b] = P("tp")
        for dw in down_ws:
            table[dw] = P("tp", None)
            # row-proj bias stays replicated (added after the psum)
    n_projs = sum(1 for op in fwd_ops if is_proj(op))
    if not table and n_projs >= 4:
        # conservatism is deliberate; silence is not (VERDICT r3 weak
        # #7): a projection-heavy program yielding NO rules means every
        # pair chase escaped — the user asked for TP and gets none
        warnings.warn(
            f"derive_sharding_rules: program has {n_projs} projections "
            f"but no tensor-parallel rules could be derived (every "
            f"pair chase escaped through a non-pass op); params will "
            f"be REPLICATED. Pass explicit sharding_rules if TP is "
            f"required.", stacklevel=2)
    return DerivedRules(table)


_downgrade_warned = set()


def safe_spec(mesh: Mesh, spec: P, shape, name: Optional[str] = None) -> P:
    """Drop a spec whose sharded dims don't divide the mesh axis
    (e.g. the (1,)-shaped beta-pow accumulator inheriting its bias
    param's P('tp')): replicate instead of erroring at device_put.

    A downgrade of a real (non-trivial-dim) param is WARNED once per
    name — a user asking for tp=8 must not silently get zero TP
    because d_inner % 8 != 0 (VERDICT r3 weak #6)."""
    for dim, ax in zip(shape, tuple(spec)):
        if ax is None:
            continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        size = 1
        for a in axes:
            size *= mesh.shape.get(a, 1)
        if size and dim % size != 0:
            if dim > 1:
                key = (name, tuple(shape), tuple(spec))
                if key not in _downgrade_warned:
                    _downgrade_warned.add(key)
                    warnings.warn(
                        f"param {name or '<unnamed>'} shape "
                        f"{tuple(shape)}: dim {dim} does not divide "
                        f"mesh axes {axes} (size {size}); sharding "
                        f"spec {spec} downgraded to replicated",
                        stacklevel=2)
            return P()
    return spec


def shard_state(state: Dict, mesh: Mesh,
                rules: Optional[ShardingRules] = None) -> Dict:
    """Place a scope state-dict on the mesh per rules (params replicated
    across dp, TP-sharded where rules/heuristics say)."""
    out = {}
    for name, val in state.items():
        if val is None:
            out[name] = val
            continue
        shape = getattr(val, "shape", ())
        spec = safe_spec(mesh, spec_for_param(name, shape, rules),
                         shape, name=name)
        out[name] = jax.device_put(val, NamedSharding(mesh, spec))
    return out


def replicate(value, mesh: Mesh):
    return jax.device_put(value, NamedSharding(mesh, P()))
