"""SPMD pipeline parallelism over a 'pp' mesh axis.

A capability beyond the reference (SURVEY.md §2.4: pipeline parallelism
ABSENT) built the TPU way: instead of per-stage processes exchanging
activations over RPC, every device runs the SAME shard_map program; stage
parameters are sharded over 'pp' (leading stacked-layer dim), and
activations advance one stage per tick via `ppermute` around the ICI
ring -- the GPipe schedule expressed as a `lax.scan` so XLA can overlap
the collective with stage compute. Differentiable with standard AD
(scan + ppermute both have transpose rules), so the full train step can
run under jit.

Layout contract:
  * stacked_params: pytree whose leaves have leading dim n_stages,
    sharded P('pp', ...) -- inside the body each device sees its own
    stage slice (leading dim 1, squeezed before calling stage_fn).
  * x: [n_micro, micro_batch, ...] microbatched input, replicated.
  * stage_fn(stage_params, x_micro) -> y_micro, same shape each stage.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _vary(x, axis_name):
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    return lax.pvary(x, axis_name)


def pipeline_local(stage_fn: Callable, stage_params, xs, axis_name: str):
    """shard_map body. stage_params: this device's stage slice (leading
    dim 1); xs: [n_micro, mb, ...] replicated microbatches. Returns
    [n_micro, mb, ...] pipeline outputs (valid on every device)."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)
    n_micro = xs.shape[0]
    mb_shape = xs.shape[1:]
    total = n_micro + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    state0 = _vary(jnp.zeros(mb_shape, xs.dtype), axis_name)
    outs0 = _vary(jnp.zeros_like(xs), axis_name)

    def tick(carry, t):
        state, outs = carry
        # stage 0 pulls microbatch t from the feed (clamped index; the
        # tail ticks feed garbage that never reaches an output slot)
        feed = lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, n_micro - 1), keepdims=False)
        inp = jnp.where(idx == 0, feed, state)
        out = stage_fn(params, inp)
        # the LAST stage's output at tick t is microbatch t-(n-1)
        slot = t - (n - 1)
        write = jnp.logical_and(idx == n - 1,
                                jnp.logical_and(slot >= 0,
                                                slot < n_micro))
        upd = lax.dynamic_update_index_in_dim(
            outs, out.astype(xs.dtype)[None], jnp.clip(slot, 0, n_micro - 1), 0)
        outs = jnp.where(write, upd, outs)
        state = lax.ppermute(out, axis_name, perm)
        return (state, outs), None

    (_, outs), _ = lax.scan(tick, (state0, outs0), jnp.arange(total))
    # outputs live on the last stage; zero elsewhere -> psum broadcasts
    outs = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis_name)


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                   n_micro: int, axis: str = "pp"):
    """Run `n_stages = mesh.shape[axis]` pipeline stages over x.

    x: [batch, ...] -- reshaped to n_micro microbatches internally.
    stacked_params leaves: [n_stages, ...] (sharded over `axis` here).
    """
    n_stages = mesh.shape[axis]
    b = x.shape[0]
    assert b % n_micro == 0, f"batch {b} % n_micro {n_micro} != 0"
    xs = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    pspec = P(axis)
    stacked_params = jax.tree.map(
        lambda p: jax.device_put(p, NamedSharding(mesh, P(axis))),
        stacked_params)
    xs = jax.device_put(xs, NamedSharding(mesh, P()))

    body = functools.partial(pipeline_local, stage_fn,
                             axis_name=axis)
    fn = jax.shard_map(
        lambda sp, xs_: body(sp, xs_),
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: pspec, stacked_params), P()),
        out_specs=P())
    ys = fn(stacked_params, xs)
    return ys.reshape((b,) + ys.shape[2:])


def dryrun(n_devices: int) -> None:
    """Driver smoke: 2-stage MLP pipeline on a pp mesh, checked against
    the sequential composition of the stages."""
    import numpy as np

    from .mesh import make_mesh, MeshConfig

    pp = 2 if n_devices % 2 == 0 else 1
    if pp == 1:
        print("dryrun pp: skipped (odd device count)")
        return
    mesh = make_mesh(MeshConfig(pp=pp), devices=jax.devices()[:pp])

    d = 16
    r = np.random.RandomState(0)
    w = jnp.asarray(r.randn(pp, d, d).astype(np.float32) * 0.3)
    b = jnp.asarray(r.randn(pp, d).astype(np.float32) * 0.1)
    x = jnp.asarray(r.randn(8, d).astype(np.float32))

    def stage_fn(params, h):
        wi, bi = params
        return jnp.tanh(h @ wi + bi)

    got = pipeline_apply(stage_fn, (w, b), x, mesh, n_micro=4)
    want = x
    for i in range(pp):
        want = jnp.tanh(want @ w[i] + b[i])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    print(f"dryrun pp: {pp}-stage GPipe schedule matches sequential ok")
