"""Deep Gradient Compression (Lin et al., ICLR'18).

Parity target: reference DGCMomentumOptimizer (python/paddle/fluid/
optimizer.py:589) + the encoded sparse allreduce in
paddle/fluid/framework/details/all_reduce_op_handle.cc:65-227, which
top-k-selects each worker's accumulated velocity, allgathers the
(index, value) pairs over NCCL, and applies the summed sparse gradient.

TPU-native split of the same algorithm:

* ``dgc_momentum_step`` -- the per-worker math (momentum correction,
  residual accumulation, threshold selection, momentum factor masking)
  as one pure jittable function. Selection uses a quantile threshold
  instead of a fixed-k top-k so the rampup *schedule* (sparsity grows
  over rampup_step steps) stays a traced scalar: XLA needs static
  shapes, and quantile keeps the mask dense-shaped while k varies.
  This is what the ``dgc_momentum`` op runs; under a GSPMD
  data-parallel program the incoming grad is already the global mean,
  so no explicit collective appears here.
* ``compressed_allreduce`` -- the explicit-communication form for
  shard_map programs (multi-worker collective mode): local top-k,
  ``all_gather`` of 2k values+indices per worker over ICI (the
  compressed wire format, vs n for a dense psum), scatter-add back to
  dense. This is the all_reduce_op_handle.cc analogue.
* ``dgc_allreduce_step`` -- full per-worker DGC step for use inside
  ``shard_map``: local correction + compressed allreduce + sparse
  update.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["rampup_sparsity", "dgc_momentum_step",
           "compressed_allreduce", "dgc_allreduce_step"]


def rampup_sparsity(step, sparsity, rampup_begin_step, rampup_step):
    """Traced sparsity schedule (reference optimizer.py:589 ctor args:
    sparsity is a warmup LIST walked over rampup_step steps after
    rampup_begin_step; before that, sparsity 0 = dense momentum)."""
    sparsity = jnp.asarray(sparsity, jnp.float32)
    n = sparsity.shape[0]
    # how far into the rampup we are, in [0, n-1]
    t = (step - rampup_begin_step).astype(jnp.float32)
    seg = jnp.clip(jnp.floor(t * n / max(rampup_step, 1)), 0, n - 1)
    s = sparsity[seg.astype(jnp.int32)]
    return jnp.where(step < rampup_begin_step, 0.0, s)


def dgc_momentum_step(p, g, u, v, lr, *, mu, step, sparsity,
                      rampup_begin_step, rampup_step,
                      use_nesterov=False):
    """One DGC momentum step on one (already-reduced) gradient.

    Pre-rampup this is EXACTLY the momentum op (ops/optimizer_ops.py
    momentum kernel), which the loss-parity test asserts. Post-rampup:
    u <- mu*u + g; v <- v + u; send = v masked to the top (1-s)
    fraction by |v| (quantile threshold); v,u <- momentum factor
    masking; p <- p - lr * send.
    """
    s = rampup_sparsity(step, sparsity, rampup_begin_step, rampup_step)

    # dense momentum branch (pre-rampup)
    u_dense = mu * u + g
    if use_nesterov:
        p_dense = p - lr * (g + mu * u_dense)
    else:
        p_dense = p - lr * u_dense

    # DGC branch
    u_c = mu * u + g
    v_c = v + u_c
    flat = jnp.abs(v_c.ravel())
    thr = jnp.quantile(flat, jnp.clip(s, 0.0, 1.0))
    # strictly-below-threshold stays local; >= is sent (s=0 sends all)
    mask = (jnp.abs(v_c) >= thr) | (s <= 0.0)
    send = jnp.where(mask, v_c, 0.0)
    v_dgc = jnp.where(mask, 0.0, v_c)
    u_dgc = jnp.where(mask, 0.0, u_c)
    p_dgc = p - lr * send

    dense = step < rampup_begin_step
    p_out = jnp.where(dense, p_dense, p_dgc)
    u_out = jnp.where(dense, u_dense, u_dgc)
    v_out = jnp.where(dense, v, v_dgc)
    return p_out, u_out, v_out


def compressed_allreduce(v, k, axis_name):
    """Sparse allreduce of each worker's top-k |v| entries.

    Wire format is (indices, values) x world over ICI -- 2*k*W numbers
    vs n for dense psum, the same compression all_reduce_op_handle.cc
    gets from its encoded NCCL allgather. Returns (dense_sum, mask)
    where mask marks THIS worker's transmitted entries.
    """
    flat = v.ravel()
    _, idx = lax.top_k(jnp.abs(flat), k)
    vals = flat[idx]
    all_idx = lax.all_gather(idx, axis_name)    # [W, k]
    all_val = lax.all_gather(vals, axis_name)   # [W, k]
    dense = jnp.zeros_like(flat).at[all_idx.ravel()].add(
        all_val.ravel())
    mask = jnp.zeros_like(flat, bool).at[idx].set(True)
    return dense.reshape(v.shape), mask.reshape(v.shape)


def dgc_allreduce_step(p, g, u, v, lr, *, mu, k, axis_name,
                       n_workers=None):
    """Per-worker DGC step for shard_map: local momentum correction,
    compressed allreduce of the top-k accumulated velocity, sparse
    param update with the SUM of workers' contributions divided by the
    worker count (parity with the dense mean-gradient convention used
    by the data-parallel executor)."""
    if n_workers is None:
        n_workers = lax.psum(1, axis_name)
    u = mu * u + g
    v = v + u
    agg, mask = compressed_allreduce(v, k, axis_name)
    v = jnp.where(mask, 0.0, v)
    u = jnp.where(mask, 0.0, u)
    p = p - lr * agg / n_workers
    return p, u, v
