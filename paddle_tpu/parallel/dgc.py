"""Deep Gradient Compression (Lin et al., ICLR'18).

Parity target: reference DGCMomentumOptimizer (python/paddle/fluid/
optimizer.py:589) + the encoded sparse allreduce in
paddle/fluid/framework/details/all_reduce_op_handle.cc:65-227, which
top-k-selects each worker's accumulated velocity, allgathers the
(index, value) pairs over NCCL, and applies the summed sparse gradient.

TPU-native split of the same algorithm:

* ``dgc_momentum_step`` -- the per-worker math (momentum correction,
  residual accumulation, threshold selection, momentum factor masking)
  as one pure jittable function. Selection uses a quantile threshold
  instead of a fixed-k top-k so the rampup *schedule* (sparsity grows
  over rampup_step steps) stays a traced scalar: XLA needs static
  shapes, and quantile keeps the mask dense-shaped while k varies.
  This is what the ``dgc_momentum`` op runs; under a GSPMD
  data-parallel program the incoming grad is already the global mean,
  so no explicit collective appears here.
* ``compressed_allreduce`` -- the explicit-communication form for
  shard_map programs (multi-worker collective mode): local top-k,
  ``all_gather`` of 2k values+indices per worker over ICI (the
  compressed wire format, vs n for a dense psum), scatter-add back to
  dense. This is the all_reduce_op_handle.cc analogue.
* ``dgc_allreduce_step`` -- full per-worker DGC step for use inside
  ``shard_map``: local correction + compressed allreduce + sparse
  update.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["rampup_sparsity", "dgc_momentum_step", "dgc_encode",
           "compressed_allreduce", "dgc_allreduce_step"]


def rampup_sparsity(step, sparsity, rampup_begin_step, rampup_step):
    """Traced sparsity schedule (reference optimizer.py:589 ctor args:
    sparsity is a warmup LIST walked over rampup_step steps after
    rampup_begin_step; before that, sparsity 0 = dense momentum)."""
    sparsity = jnp.asarray(sparsity, jnp.float32)
    n = sparsity.shape[0]
    # how far into the rampup we are, in [0, n-1]
    t = (step - rampup_begin_step).astype(jnp.float32)
    seg = jnp.clip(jnp.floor(t * n / max(rampup_step, 1)), 0, n - 1)
    s = sparsity[seg.astype(jnp.int32)]
    return jnp.where(step < rampup_begin_step, 0.0, s)


def _correct_and_select(u, v, g, *, m, s, use_nesterov=False):
    """Momentum correction + quantile-threshold selection — the shared
    core of `dgc_momentum_step` and the `dgc` encode op (reference
    dgc_op.h:90-110 correction; k_select). Returns
    (u_c, v_c, mask, send): corrected accumulators, the transmit mask
    (strictly-below-threshold stays local; >= is sent; s=0 sends all),
    and the masked send tensor."""
    if use_nesterov:
        u_c = m * (u + g)
        v_c = v + u_c + g
    else:
        u_c = m * u + g
        v_c = v + u_c
    flat = jnp.abs(v_c.ravel())
    thr = jnp.quantile(flat, jnp.clip(s, 0.0, 1.0))
    mask = (jnp.abs(v_c) >= thr) | (s <= 0.0)
    send = jnp.where(mask, v_c, 0.0)
    return u_c, v_c, mask, send


def dgc_momentum_step(p, g, u, v, lr, *, mu, step, sparsity,
                      rampup_begin_step, rampup_step,
                      use_nesterov=False):
    """One DGC momentum step on one (already-reduced) gradient.

    Pre-rampup this is EXACTLY the momentum op (ops/optimizer_ops.py
    momentum kernel), which the loss-parity test asserts. Post-rampup:
    u <- mu*u + g; v <- v + u; send = v masked to the top (1-s)
    fraction by |v| (quantile threshold); v,u <- momentum factor
    masking; p <- p - lr * send.
    """
    s = rampup_sparsity(step, sparsity, rampup_begin_step, rampup_step)

    # dense momentum branch (pre-rampup)
    u_dense = mu * u + g
    if use_nesterov:
        p_dense = p - lr * (g + mu * u_dense)
    else:
        p_dense = p - lr * u_dense

    # DGC branch (correction is non-nesterov here regardless: the
    # nesterov lookahead is already in the dense-branch update rule)
    u_c, v_c, mask, send = _correct_and_select(u, v, g, m=mu, s=s)
    v_dgc = jnp.where(mask, 0.0, v_c)
    u_dgc = jnp.where(mask, 0.0, u_c)
    p_dgc = p - lr * send

    dense = step < rampup_begin_step
    p_out = jnp.where(dense, p_dense, p_dgc)
    u_out = jnp.where(dense, u_dense, u_dgc)
    v_out = jnp.where(dense, v, v_dgc)
    return p_out, u_out, v_out


def dgc_encode(u, v, g, *, m, step, sparsity, rampup_begin_step,
               rampup_step, use_nesterov=False):
    """The `dgc` (encode) op's math (reference operators/dgc_op.h:38
    DGCOpKernel + dgc_op.cc:63 DGCOpMaker).

    Reference semantics: momentum-correct the accumulators
    (u <- m*u + g; v <- v + u; nesterov: u <- m*(u+g); v <- v + u + g),
    k_select the top |v| entries into EncodeGrad, zero them out of
    u/v, and zero Grad_out (the encoded tensor replaces the dense
    gradient on the wire). Pre-rampup (step < rampup_begin_step) the
    op is a no-op and the dense gradient passes through.

    TPU-native differences: EncodeGrad is a DENSE masked tensor (same
    shape as Grad, zeros at unsent positions) rather than the
    reference's 2k-element (index, value) buffer — XLA needs static
    shapes while k varies with the rampup schedule, and the actual
    2k-per-worker wire format lives in `compressed_allreduce` for
    shard_map programs. Selection is by quantile threshold (see
    `rampup_sparsity`), keeping k a traced scalar.

    Returns (u_out, v_out, encode_grad, grad_out, k).
    """
    s = rampup_sparsity(step, sparsity, rampup_begin_step, rampup_step)
    u_c, v_c, mask, encode = _correct_and_select(
        u, v, g, m=m, s=s, use_nesterov=use_nesterov)
    k = jnp.sum(mask.astype(jnp.float32))

    dense = step < rampup_begin_step
    u_out = jnp.where(dense, u, jnp.where(mask, 0.0, u_c))
    v_out = jnp.where(dense, v, jnp.where(mask, 0.0, v_c))
    encode = jnp.where(dense, jnp.zeros_like(encode), encode)
    # post-rampup the dense grad is replaced by the encoded wire
    # (reference zeroes Grad_out); pre-rampup it passes through
    grad_out = jnp.where(dense, g, jnp.zeros_like(g))
    k = jnp.where(dense, 0.0, k)
    return u_out, v_out, encode, grad_out, k


def compressed_allreduce(v, k, axis_name):
    """Sparse allreduce of each worker's top-k |v| entries.

    Wire format is (indices, values) x world over ICI -- 2*k*W numbers
    vs n for dense psum, the same compression all_reduce_op_handle.cc
    gets from its encoded NCCL allgather. Returns (dense_sum, mask)
    where mask marks THIS worker's transmitted entries.
    """
    flat = v.ravel()
    _, idx = lax.top_k(jnp.abs(flat), k)
    vals = flat[idx]
    all_idx = lax.all_gather(idx, axis_name)    # [W, k]
    all_val = lax.all_gather(vals, axis_name)   # [W, k]
    dense = jnp.zeros_like(flat).at[all_idx.ravel()].add(
        all_val.ravel())
    mask = jnp.zeros_like(flat, bool).at[idx].set(True)
    return dense.reshape(v.shape), mask.reshape(v.shape)


def dgc_allreduce_step(p, g, u, v, lr, *, mu, k, axis_name,
                       n_workers=None):
    """Per-worker DGC step for shard_map: local momentum correction,
    compressed allreduce of the top-k accumulated velocity, sparse
    param update with the SUM of workers' contributions divided by the
    worker count (parity with the dense mean-gradient convention used
    by the data-parallel executor)."""
    if n_workers is None:
        n_workers = lax.psum(1, axis_name)
    u = mu * u + g
    v = v + u
    agg, mask = compressed_allreduce(v, k, axis_name)
    v = jnp.where(mask, 0.0, v)
    u = jnp.where(mask, 0.0, u)
    p = p - lr * agg / n_workers
    return p, u, v
