"""Parallelism: mesh/sharding rules, distributed bootstrap, collectives.

TPU-native replacement for the reference's two distributed stacks
(SURVEY.md §2.4): NCCL op-handle data parallelism and the gRPC/BRPC
parameter-server transpiler. Communication is compiler-scheduled XLA
collectives over ICI/DCN via jax.sharding annotations -- not runtime
op handles.
"""
from .mesh import make_mesh, MeshConfig  # noqa: F401
from .sharding import (ShardingRules, default_transformer_rules,
                       shard_state, replicate)  # noqa: F401
from .env import DistributedEnv, init_distributed_env  # noqa: F401
from .ring_attention import (ring_self_attention, context_parallel,
                             ring_attention_local,
                             ulysses_attention_local)  # noqa: F401
from .pipeline import pipeline_apply  # noqa: F401
from .moe import moe_apply, expert_parallel  # noqa: F401
from .pipeline_program import (PipelineTrainer,
                               propose_loops)  # noqa: F401
