"""Pipeline parallelism as a FRAMEWORK capability: partition a built
`Program` into stages and train it with the GPipe schedule.

Reference precedent for program surgery:
multi_devices_graph_pass.h:40,110 (the reference replicates and
rewrites the graph per device); the capability itself is beyond the
reference (SURVEY.md §2.4: pipeline parallelism ABSENT in Fluid).

TPU-native design
-----------------
A Fluid-style training program is (forward ops | backward ops |
optimizer ops). This pass:

* keeps the program's FORWARD and OPTIMIZER ops, drops its backward
  ops (JAX AD through the pipeline replaces them — `lax.scan` and
  `ppermute` both have transpose rules, so one `jax.grad` covers the
  bubble schedule, the microbatch accumulation, and the stage
  collectives);
* splits the forward into replicated sections and LOOP sections. A
  loop section is a run of isomorphic segments (e.g. the N
  transformer layers), named by its boundary activations
  ``bounds = [b0, b1, ..., bk]`` (b0 = input of segment 1, bi =
  output of segment i). Segments are validated isomorphic (same op
  types/attrs, same param shapes) and are executed by ONE traced
  copy of segment 0's ops with per-segment params bound positionally;
* per-segment params are stacked to a leading [n_segments] dim. With
  ``pp == 1`` the loop lowers to `lax.scan` over layers — the HLO
  stops growing linearly in depth (compile-size fix). With
  ``pp > 1`` the stacked dim is sharded over the 'pp' mesh axis and
  the loop runs the GPipe schedule: every device executes
  n_segments/pp consecutive segments, activations advance one stage
  per tick via `ppermute` around the ICI ring, microbatches ride the
  same ring (gradient accumulation across microbatches is the scan's
  AD, not hand-written);
* broadcast inputs (vars produced before the loop and read inside it,
  e.g. the encoder output consumed by every decoder layer's cross
  attention) ride the ring NEXT TO their microbatch when they are
  batch-major, and are passed replicated otherwise;
* the program's own optimizer/lr-scheduler/clip ops then run on the
  AD gradients (bound under the reference's `param@GRAD` names), so
  optimizer semantics — noam decay, Adam bias correction, grad
  clipping — are EXACTLY the Executor path's, and single-device loss
  parity holds to float tolerance.

Usage::

    main, startup, loss = transformer.build_program(...)
    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    tr = PipelineTrainer(main, loss, loops=[enc_bounds, dec_bounds],
                         mesh=mesh, n_micro=4)
    exe.run(startup, scope=scope)
    tr.initialize(scope)
    for batch in data:
        loss_val = tr.run(feed=batch)
    tr.write_back(scope)   # params/optimizer state back to the scope
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.program import GRAD_SUFFIX, Program, grad_var_name
from ..core.registry import EMPTY_VAR, is_registered, run_op

__all__ = ["PipelineTrainer", "PipelinePartitionError", "propose_loops"]


class PipelinePartitionError(ValueError):
    """Raised when a Program cannot be partitioned as requested."""


class PipelineFetchError(KeyError):
    """A fetch target the pipeline schedule does not materialize.
    Distinct from a plain KeyError (e.g. a missing feed) so callers
    like CompiledProgram can rebuild with widened fetch hints on THIS
    error only."""


# ---------------------------------------------------------------------------
# partition
# ---------------------------------------------------------------------------
@dataclass
class _Loop:
    bounds: List[str]                     # [b0 .. bk]
    segments: List[List] = field(default_factory=list)   # ops per segment
    # canonical (segment-0) param names, positional order
    canon_params: List[str] = field(default_factory=list)
    # per-segment param names aligned with canon_params
    seg_params: List[List[str]] = field(default_factory=list)
    bcast: List[str] = field(default_factory=list)       # broadcast reads
    # per-segment outputs read after the loop (MoE aux pattern):
    # outer list = positional family, inner = one name per segment
    reduce_outs: List[List[str]] = field(default_factory=list)


@dataclass
class _Section:
    kind: str                 # "repl" | "loop"
    ops: List = field(default_factory=list)
    loop: Optional[_Loop] = None


def _op_reads(op):
    return [n for names in op.inputs.values() for n in names
            if n != EMPTY_VAR]


def _op_writes(op):
    return [n for names in op.outputs.values() for n in names
            if n != EMPTY_VAR]


def _is_backward(op):
    return op.attrs.get("op_role") == "backward"


def _persistable(block, name):
    v = block._find_var_recursive(name)
    return v is not None and v.persistable


def _touches_grad(op):
    return any(GRAD_SUFFIX in n
               for n in _op_reads(op) + _op_writes(op))


def _attrs_isomorphic(a, b):
    ka = {k: v for k, v in a.items() if k != "op_role"}
    kb = {k: v for k, v in b.items() if k != "op_role"}
    return ka == kb


def _partition(program: Program, loss_name: str,
               loops_bounds: Sequence[Sequence[str]],
               fetch_hints: Sequence[str] = ()):
    """Split the block into (sections, phaseB ops, var metadata)."""
    block = program.global_block
    for op in block.ops:
        if op.type in ("feed", "fetch"):
            continue
        if not is_registered(op.type):
            raise PipelinePartitionError(
                f"op {op.type!r} has no registered kernel")
        if any(hasattr(v, "ops") for v in op.attrs.values()):
            raise PipelinePartitionError(
                f"op {op.type!r} carries a sub-block; control-flow "
                f"programs cannot be pipeline-partitioned")

    kept = [op for op in block.ops
            if op.type not in ("feed", "fetch") and not _is_backward(op)]
    # phase B = optimizer tail: first kept op that is optimize-role or
    # touches a @GRAD var; everything after runs on the AD gradients
    b_start = len(kept)
    for i, op in enumerate(kept):
        if op.attrs.get("op_role") == "optimize" or _touches_grad(op):
            b_start = i
            break
    phase_a, phase_b = kept[:b_start], kept[b_start:]

    if not any(loss_name in _op_writes(op) for op in phase_a):
        raise PipelinePartitionError(
            f"loss var {loss_name!r} is not produced by the forward "
            f"section")

    def persistable(name):
        return _persistable(block, name)

    def is_data(name):
        v = block._find_var_recursive(name)
        return v is not None and v.is_data

    # producer index (last writer) of every var within phase A
    producer = {}
    for i, op in enumerate(phase_a):
        for n in _op_writes(op):
            producer[n] = i

    # resolve loop op ranges
    ranges = []
    for bounds in loops_bounds:
        bounds = [b.name if hasattr(b, "name") else b for b in bounds]
        if len(bounds) < 3:
            raise PipelinePartitionError(
                f"loop bounds {bounds} must name at least two segments "
                f"(>=3 boundary vars)")
        for b in bounds[1:]:
            if b not in producer:
                raise PipelinePartitionError(
                    f"loop boundary var {b!r} is not produced by the "
                    f"forward section")
        if bounds[0] not in producer and not is_data(bounds[0]):
            raise PipelinePartitionError(
                f"loop input var {bounds[0]!r} is neither produced by "
                f"the forward section nor a data var")
        # a data-var loop input means the loop starts at op 0
        idxs = [producer.get(bounds[0], -1)] + \
            [producer[b] for b in bounds[1:]]
        if idxs != sorted(idxs):
            raise PipelinePartitionError(
                f"loop bounds {bounds} are not in program order")
        ranges.append((idxs[0], idxs[-1], bounds, idxs))
    ranges.sort()
    for (s1, e1, b1, _), (s2, e2, b2, _) in zip(ranges, ranges[1:]):
        if s2 < e1:
            raise PipelinePartitionError(
                f"loops {b1[-1]} and {b2[0]} overlap")

    # build sections
    sections: List[_Section] = []
    cursor = 0
    for start, end, bounds, idxs in ranges:
        if cursor <= start:
            repl = phase_a[cursor:start + 1]
            if repl:
                sections.append(_Section("repl", ops=repl))
        loop = _Loop(bounds=bounds)
        for a, b in zip(idxs, idxs[1:]):
            loop.segments.append(phase_a[a + 1:b + 1])
        sections.append(_Section("loop", loop=loop))
        cursor = end + 1
    tail = phase_a[cursor:]
    if tail:
        sections.append(_Section("repl", ops=tail))

    # analyze + validate each loop
    for sec in sections:
        if sec.kind != "loop":
            continue
        loop = sec.loop
        pre_loop = set()
        for s in sections:
            if s is sec:
                break
            if s.kind == "repl":
                for op in s.ops:
                    pre_loop.update(_op_writes(op))
            else:
                pre_loop.update(s.loop.bounds)
        n_ops = [len(seg) for seg in loop.segments]
        if len(set(n_ops)) != 1:
            raise PipelinePartitionError(
                f"loop {loop.bounds[0]}..{loop.bounds[-1]}: segments "
                f"have differing op counts {n_ops}; not isomorphic")
        types0 = [op.type for op in loop.segments[0]]
        for si, seg in enumerate(loop.segments[1:], 1):
            types = [op.type for op in seg]
            if types != types0:
                raise PipelinePartitionError(
                    f"loop segment {si} op types {types} differ from "
                    f"segment 0 {types0}; not isomorphic")
            for o0, oi in zip(loop.segments[0], seg):
                if not _attrs_isomorphic(o0.attrs, oi.attrs):
                    raise PipelinePartitionError(
                        f"loop segment {si} op {oi.type!r} attrs "
                        f"differ from segment 0; not isomorphic")
        bcast = []
        read_sigs = []
        for si, seg in enumerate(loop.segments):
            local = set()
            params_i = []
            sig = []   # positional read signature, compared across segs
            bound_in = loop.bounds[si]
            for op in seg:
                for n in _op_writes(op):
                    if persistable(n):
                        raise PipelinePartitionError(
                            f"loop segment {si}: op {op.type!r} writes "
                            f"persistable {n!r}; stateful ops (e.g. "
                            f"batch-norm running stats) inside a "
                            f"pipelined loop are not supported — their "
                            f"updates cannot be threaded out of the "
                            f"stage scan")
                for n in _op_reads(op):
                    if n == bound_in:
                        sig.append("@BOUND")
                        continue
                    if n in local:
                        sig.append("@LOCAL")
                        continue
                    if persistable(n):
                        sig.append("@PARAM")
                        if n not in params_i:
                            params_i.append(n)
                    elif n in pre_loop or is_data(n):
                        # broadcasts are traced once (segment 0's ops
                        # serve every segment) -> the NAME must match
                        # across segments, so it goes into the
                        # signature verbatim
                        sig.append(n)
                        if n not in bcast:
                            bcast.append(n)
                    else:
                        raise PipelinePartitionError(
                            f"loop segment {si}: op {op.type!r} reads "
                            f"{n!r}, which is produced in another "
                            f"segment (cross-segment skip connections "
                            f"are not pipelineable)")
                local.update(_op_writes(op))
            if loop.bounds[si + 1] not in local:
                raise PipelinePartitionError(
                    f"loop segment {si} does not produce its boundary "
                    f"var {loop.bounds[si + 1]!r}")
            loop.seg_params.append(params_i)
            read_sigs.append(sig)
        for si, sig in enumerate(read_sigs[1:], 1):
            if sig != read_sigs[0]:
                diff = next(
                    (a, b) for a, b in zip(read_sigs[0], sig)
                    if a != b)
                raise PipelinePartitionError(
                    f"loop segment {si} reads {diff[1]!r} where "
                    f"segment 0 reads {diff[0]!r}; per-segment "
                    f"broadcast inputs must be identical (segment 0's "
                    f"trace serves every segment)")
        loop.canon_params = loop.seg_params[0]
        lens = [len(p) for p in loop.seg_params]
        if len(set(lens)) != 1:
            raise PipelinePartitionError(
                f"loop segments have differing param counts {lens}")
        # stacked execution binds every segment's params positionally
        # to ONE trace: declared shapes must match or the stack is
        # malformed (e.g. an input-projection first layer whose weight
        # is [d_in, d] vs the stack's [d, d])
        for pos in range(lens[0]):
            shapes = []
            for si in range(len(loop.seg_params)):
                v = block._find_var_recursive(loop.seg_params[si][pos])
                shapes.append(tuple(v.shape) if v is not None else None)
            if len(set(shapes)) != 1:
                names = [p[pos] for p in loop.seg_params]
                raise PipelinePartitionError(
                    f"loop params {names} have differing declared "
                    f"shapes {shapes}; segments are not isomorphic "
                    f"(keep shape-changing layers outside the loop "
                    f"bounds)")
        loop.bcast = bcast

        # reduce outputs: vars written inside a segment and read AFTER
        # the loop (the MoE per-layer aux-loss pattern). They are
        # emitted per segment by the scan/GPipe schedule; microbatched
        # schedules average them over microbatches (documented: the
        # Switch aux is nonlinear in the batch, so pp>1 values are the
        # mean of per-microbatch routing statistics).
        seen_self = False
        later_reads = set()
        for s in sections:
            if s is sec:
                seen_self = True
                continue
            if not seen_self:
                continue
            ops_ = s.ops if s.kind == "repl" else \
                [op for seg in s.loop.segments for op in seg]
            for op in ops_:
                later_reads.update(_op_reads(op))
        for op in phase_b:
            later_reads.update(_op_reads(op))
        # fetch hints promote otherwise-dead per-segment outputs (the
        # MoE drop-fraction observability pattern) into reduce-out
        # families so the schedules materialize them; under pp > 1
        # they come back as per-microbatch means like every reduce
        # out. Batch-major vars are excluded: a mean over microbatches
        # of per-example activations is not the Executor's value, so
        # those stay a named fetch error instead of a silent surprise.
        # static-batch programs declare a CONCRETE batch on their data
        # vars; a loop internal with that same leading dim is
        # per-example too
        static_batches = {
            v.shape[0] for v in block.vars.values()
            if v.is_data and v.shape and v.shape[0] != -1}

        def _hintable(name):
            v = block._find_var_recursive(name)
            return (v is not None and v.shape and not v.is_data
                    and not v.persistable and v.shape[0] != -1
                    and v.shape[0] not in static_batches)

        later_reads.update(n for n in fetch_hints if _hintable(n))

        def _out_positions(seg):
            pos = []
            for oi, op in enumerate(seg):
                for slot, names in op.outputs.items():
                    for k, nm in enumerate(names):
                        if nm in later_reads and nm != loop.bounds[-1]:
                            pos.append((oi, slot, k))
            return pos

        # positions are the UNION over segments: reading (or hinting)
        # only segment 2's observable still exports the whole family
        pos_union = sorted({p for seg in loop.segments
                            for p in _out_positions(seg)})
        for si, seg in enumerate(loop.segments):
            for (oi, slot, k) in pos_union:
                names = seg[oi].outputs.get(slot, [])
                if k >= len(names):
                    raise PipelinePartitionError(
                        f"loop segment {si}: op {seg[oi].type!r} has "
                        f"no output at slot {slot}[{k}] that other "
                        f"segments export as a reduce output")
        loop.reduce_outs = [
            [seg[oi].outputs[slot][k] for seg in loop.segments]
            for (oi, slot, k) in pos_union]

    return sections, phase_b


# ---------------------------------------------------------------------------
# auto-detection helper
# ---------------------------------------------------------------------------
def propose_loops(program: Program, loss_name: str,
                  min_segments: int = 2) -> List[List[str]]:
    """Detect maximal runs of isomorphic op segments in the forward
    section and return their boundary-var lists (candidate `loops`
    arguments). Convenience over manual bound naming; validation still
    happens in `_partition`."""
    sections, _ = _partition(program, loss_name, [])
    ops = [op for sec in sections for op in sec.ops]
    types = [op.type for op in ops]
    n = len(types)
    block = program.global_block

    def persistable(name):
        return _persistable(block, name)

    # collect every valid periodic run, then greedily keep the ones
    # covering the most ops (a transformer layer beats the 2-op
    # bias-add mini-runs nested inside it)
    candidates = []

    def _param_shapes(op):
        return [tuple(v.shape)
                for names in op.inputs.values() for n in names
                if n != EMPTY_VAR and persistable(n)
                and (v := block._find_var_recursive(n)) is not None]

    def _iso(a_off, b_off, period):
        return (types[b_off:b_off + period] ==
                types[a_off:a_off + period] and
                all(_attrs_isomorphic(ops[a_off + i].attrs,
                                      ops[b_off + i].attrs)
                    # positional param shapes must match too: an
                    # input-projection layer (e.g. fc 16->32 before a
                    # 32->32 stack) has identical op types/attrs but
                    # cannot join the stacked loop
                    and _param_shapes(ops[a_off + i]) ==
                    _param_shapes(ops[b_off + i])
                    for i in range(period)))

    for period in range(1, n // 2 + 1):
        start = 0
        while start + 2 * period <= n:
            m = 1
            while (start + (m + 1) * period <= n
                   and _iso(start, start + m * period, period)):
                m += 1
            if m >= min_segments:
                segs = [ops[start + i * period:
                            start + (i + 1) * period]
                        for i in range(m)]
                bounds = _infer_bounds(segs, persistable)
                if bounds is not None:
                    candidates.append(
                        (m * period, m, start, period, bounds))
                start += m * period
            else:
                start += 1
    candidates.sort(key=lambda c: (-c[0], -c[1], c[2]))
    best: List[List[str]] = []
    covered = [False] * n
    for cover, m, start, period, bounds in candidates:
        if any(covered[start:start + cover]):
            continue
        for i in range(start, start + cover):
            covered[i] = True
        best.append((start, bounds))
    return [b for _, b in sorted(best)]


def _infer_bounds(segs, persistable):
    """A run of op segments is a loop iff exactly one non-persistable
    var crosses each segment boundary; returns [b0..bk] or None."""
    bounds = []
    for i, seg in enumerate(segs):
        produced_prev = set()
        if i > 0:
            for op in segs[i - 1]:
                produced_prev.update(_op_writes(op))
        local = set()
        crossing = []
        for op in seg:
            for nm in _op_reads(op):
                if (nm in produced_prev and nm not in local
                        and not persistable(nm) and nm not in crossing):
                    crossing.append(nm)
            local.update(_op_writes(op))
        if i == 0:
            continue
        if len(crossing) != 1:
            return None
        bounds.append(crossing[0])
    if not bounds:
        return None
    # b0: the same positional input for segment 0. Find which op/slot
    # consumed the crossing var in segment 1 and read segment 0's same
    # position.
    seg1 = segs[1]
    target = bounds[0]
    pos = None
    for oi, op in enumerate(seg1):
        for slot, names in op.inputs.items():
            for k, nm in enumerate(names):
                if nm == target:
                    pos = (oi, slot, k)
                    break
            if pos:
                break
        if pos:
            break
    oi, slot, k = pos
    b0 = segs[0][oi].inputs[slot][k]
    # bk: last segment's counterpart of the crossing output
    prod_pos = None
    for oi, op in enumerate(segs[0]):
        for slot, names in op.outputs.items():
            for k, nm in enumerate(names):
                if nm == bounds[0]:
                    prod_pos = (oi, slot, k)
    if prod_pos is None:
        return None
    oi, slot, k = prod_pos
    bk = segs[-1][oi].outputs[slot][k]
    return [b0] + bounds + [bk]


# ---------------------------------------------------------------------------
# trainer
# ---------------------------------------------------------------------------
class PipelineTrainer:
    """Train a Program with its repeated-layer loops pipelined over a
    'pp' mesh axis (or scanned over layers when pp == 1)."""

    def __init__(self, program: Program, loss, *,
                 loops: Sequence[Sequence[str]],
                 mesh: Optional[Mesh] = None, n_micro: int = 1,
                 axis: str = "pp", tp_rules=None,
                 schedule: str = "gpipe",
                 fetch_hints: Sequence[str] = ()):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule must be 'gpipe' or '1f1b', got {schedule!r}")
        self.schedule = schedule
        self.fetch_hints = tuple(fetch_hints)
        self.program = program
        self.loss_name = loss.name if hasattr(loss, "name") else loss
        self.mesh = mesh
        self.axis = axis
        self.n_micro = int(n_micro)
        self.pp = 1 if mesh is None else int(mesh.shape[axis])
        self.tp = 1
        self.dp = 1
        if mesh is not None:
            # pp composes with tp AND dp: the pipeline ring is MANUAL
            # over the 'pp' axis (shard_map axis_names) while 'tp' and
            # 'dp' stay AUTO axes — GSPMD partitions the per-segment
            # matmuls by the structural rules (tp) and the microbatch
            # rows by the batch constraint (dp), inserting the grad
            # psum exactly as the dp x tp Executor path does. Other
            # axes must be size 1.
            self.tp = int(mesh.shape.get("tp", 1))
            self.dp = int(mesh.shape.get("dp", 1))
            other = [a for a in mesh.axis_names
                     if a not in (axis, "tp", "dp")
                     and mesh.shape[a] != 1]
            if other:
                raise PipelinePartitionError(
                    f"PipelineTrainer supports a {axis!r} (x 'tp' x "
                    f"'dp') mesh; axes {other} have size > 1")
        self.sections, self.phase_b = _partition(
            program, self.loss_name, loops,
            fetch_hints=self.fetch_hints)
        for sec in self.sections:
            if sec.kind == "loop" and len(sec.loop.segments) % self.pp:
                raise PipelinePartitionError(
                    f"loop {sec.loop.bounds[0]}..: "
                    f"{len(sec.loop.segments)} segments not divisible "
                    f"by pp={self.pp}")
        self._collect_state_names()
        # explicit tp_rules (a ShardingRules object) wins; otherwise
        # derive the structural table from the program graph
        self._tp_rules = tp_rules if self.tp > 1 else None
        if self.tp > 1 and tp_rules is None:
            from .sharding import derive_sharding_rules

            self._tp_rules = derive_sharding_rules(program)
        self.state: Dict[str, jax.Array] = {}
        self._rng = None
        self._jitted = None
        self._feed_spec = None

    # ------------------------------------------------------------------
    def _tp_spec(self, name, shape):
        """PartitionSpec ('tp' dims only) for one state var, downgraded
        to replicated when the dim doesn't divide."""
        from .sharding import safe_spec

        if self._tp_rules is None:
            return P()
        return safe_spec(self.mesh,
                         self._tp_rules.spec_for(name, len(shape)),
                         shape, name=name)

    def _stack_spec(self, loop, pos, shape):
        """Sharding spec for a stacked [n_seg, ...] param: 'pp' on the
        stack dim + the canon param's tp spec on its own dims. Falls
        back to pp-only if segments disagree (can't happen for loops
        that passed isomorphism validation, but stay safe)."""
        specs = {tuple(self._tp_spec(loop.seg_params[s][pos], shape))
                 for s in range(len(loop.seg_params))}
        tp_part = specs.pop() if len(specs) == 1 else ()
        lead = self.axis if self.pp > 1 else None
        return P(lead, *tp_part)

    # ------------------------------------------------------------------
    def _collect_state_names(self):
        block = self.program.global_block

        def persistable(name):
            return _persistable(block, name)

        a_ops = []
        for sec in self.sections:
            if sec.kind == "repl":
                a_ops += sec.ops
            else:
                for seg in sec.loop.segments:
                    a_ops += seg
        read_a, written_a = [], []
        produced = set()
        for op in a_ops:
            for n in _op_reads(op):
                if persistable(n) and n not in produced \
                        and n not in read_a:
                    read_a.append(n)
            for n in _op_writes(op):
                produced.add(n)
                if persistable(n) and n not in written_a:
                    written_a.append(n)
        read_b, written_b = [], []
        for op in self.phase_b:
            for n in _op_reads(op):
                if persistable(n) and n not in read_b:
                    read_b.append(n)
            for n in _op_writes(op):
                if persistable(n) and n not in written_b:
                    written_b.append(n)
        self.params_a = read_a            # forward persistables
        self.state_names = list(dict.fromkeys(
            read_a + written_a + read_b + written_b))
        self.state_out = list(dict.fromkeys(written_a + written_b))
        # feeds: data vars read anywhere in phase A
        self.feed_names = sorted({
            n for op in a_ops for n in _op_reads(op)
            if (v := block._find_var_recursive(n)) is not None
            and v.is_data})
        # phase-A-produced non-persistables read by phase B (lr etc.)
        a_local = {n for op in a_ops for n in _op_writes(op)
                   if not persistable(n)}
        self.aux_names = sorted({
            n for op in self.phase_b for n in _op_reads(op)
            if n in a_local and not n.endswith(GRAD_SUFFIX)})

    # ------------------------------------------------------------------
    def initialize(self, scope):
        """Pull params/optimizer state from a scope (run the startup
        program into it first)."""
        for n in self.state_names:
            v = scope._get(n)
            if v is None:
                raise RuntimeError(
                    f"Variable {n!r} is used before initialization -- "
                    f"run the startup program first")
            arr = jnp.asarray(np.asarray(v))
            if self.tp > 1:
                # replicated-section params (embeddings, logits head,
                # optimizer accumulators) take their structural tp spec
                # up front; loop params are re-constrained at stack time
                arr = jax.device_put(arr, NamedSharding(
                    self.mesh, self._tp_spec(n, arr.shape)))
            self.state[n] = arr
        seed = getattr(self.program, "_seed", None) or 0
        self._rng = jax.random.PRNGKey(seed)
        return self

    def write_back(self, scope):
        for n, v in self.state.items():
            scope._set(n, v)

    # ------------------------------------------------------------------
    def _seg_apply(self, loop, params_list, h, bcast_env, key, seg_idx):
        """Run segment-0's ops with positionally-bound params.
        Returns (boundary output, tuple of reduce-out values)."""
        env = dict(bcast_env)
        env[loop.bounds[0]] = h
        for name, val in zip(loop.canon_params, params_list):
            env[name] = val
        cell = [jax.random.fold_in(key, 0)]
        for op in loop.segments[0]:
            run_op(op, env, rng_cell=cell,
                   rng_salt=_fold_salt(op._uid, seg_idx))
        reds = tuple(env[fam[0]] for fam in loop.reduce_outs)
        return env[loop.bounds[1]], reds

    def _run_loop(self, loop, env, key):
        h0 = env[loop.bounds[0]]
        n_seg = len(loop.segments)
        # stack per-segment params positionally; grads flow back
        # through the stack to the per-name leaves
        stacked = []
        for pos in range(len(loop.canon_params)):
            leaves = [env[loop.seg_params[s][pos]]
                      for s in range(n_seg)]
            st = jnp.stack(leaves)
            if self.pp > 1 or self.tp > 1:
                st = lax.with_sharding_constraint(
                    st, NamedSharding(
                        self.mesh,
                        self._stack_spec(loop, pos, leaves[0].shape)))
            stacked.append(st)
        if self.pp == 1:
            # scan-over-layers: the full-batch dim IS the final
            # layout, so the dp constraint lands here (the GPipe path
            # constrains the [n_micro, mb, ...] mb dim instead — a
            # dim-0 constraint there would force a reshard)
            h0 = self._dp_shard(h0)

            def body(h, xs):
                params, j = xs
                out, reds = self._seg_apply(loop, params, h, env, key,
                                            j)
                # under AMP the boundary can come back fp32 (layer_norm
                # is a KEEP op) while the carry entered bf16; cast back
                # -- identical to the cast the next layer's first
                # white-listed op performs in the unrolled program
                return out.astype(h.dtype), reds
            h, ys = lax.scan(body, h0,
                             (tuple(stacked), jnp.arange(n_seg)))
            for fam, arr in zip(loop.reduce_outs, ys):
                for si, nm in enumerate(fam):
                    env[nm] = arr[si]
            return h
        return self._run_loop_gpipe(loop, stacked, h0, env, key)

    def _dp_shard(self, arr, batch_dim=0):
        """Constrain a batch-major array's batch dim over the AUTO
        'dp' axis (no-op at dp == 1): GSPMD then partitions the ring
        body's per-microbatch compute across dp and inserts the grad
        psum where AD needs it."""
        if self.dp <= 1:
            return arr
        spec = [None] * arr.ndim
        spec[batch_dim] = "dp"
        return lax.with_sharding_constraint(
            arr, NamedSharding(self.mesh, P(*spec)))

    def _run_loop_gpipe(self, loop, stacked, h0, env, key):
        n_micro, pp, axis = self.n_micro, self.pp, self.axis
        B = h0.shape[0]
        if B % n_micro:
            raise ValueError(
                f"batch {B} not divisible by n_micro {n_micro}")
        mb = B // n_micro
        k = len(loop.segments) // pp

        bb_names, const_names = [], []
        blk = self.program.global_block
        for n in loop.bcast:
            if _classify_batch_major(blk, n, env[n], B):
                bb_names.append(n)
            else:
                const_names.append(n)
        xs_h = self._dp_shard(
            h0.reshape((n_micro, mb) + h0.shape[1:]), 1)
        xs_bb = [self._dp_shard(
            env[n].reshape((n_micro, mb) + env[n].shape[1:]), 1)
            for n in bb_names]
        consts = [env[n] for n in const_names]

        def local(stk, xs_h, xs_bb, consts, key):
            n = lax.psum(1, axis)
            idx = lax.axis_index(axis)
            bc_env = dict(zip(const_names, consts))
            total = n_micro + n - 1
            perm = [(i, (i + 1) % n) for i in range(n)]

            def stage(h, bb, key):
                bc = dict(bc_env)
                bc.update(zip(bb_names, bb))

                def seg_body(hc, xs):
                    params, j = xs
                    out, reds = self._seg_apply(loop, params, hc, bc,
                                                key, idx * k + j)
                    # AMP boundary cast; see the pp==1 branch
                    return out.astype(hc.dtype), reds

                h, reds = lax.scan(seg_body, h,
                                   (tuple(stk), jnp.arange(k)))
                return h, reds  # reds: tuple of [k, ...] per family

            def pick(t):
                i = jnp.clip(t, 0, n_micro - 1)
                return (lax.dynamic_index_in_dim(xs_h, i, keepdims=False),
                        [lax.dynamic_index_in_dim(x, i, keepdims=False)
                         for x in xs_bb])

            h_init, bb_init = pick(jnp.asarray(0))
            h_init = _vary(h_init, axis)
            bb_init = [_vary(x, axis) for x in bb_init]
            outs0 = _vary(jnp.zeros((n_micro, mb) + h_init.shape[1:],
                                    h_init.dtype), axis)
            # reduce-out accumulators: one [k, ...] buffer per family,
            # summed over this stage's processed microbatches
            shapes = jax.eval_shape(stage, h_init, bb_init, key)[1]
            racc0 = tuple(_vary(jnp.zeros(s.shape, s.dtype), axis)
                          for s in shapes)

            def tick(carry, t):
                h, bb, outs, raccs = carry
                feed_h, feed_bb = pick(t)
                h_in = jnp.where(idx == 0, feed_h, h)
                bb_in = [jnp.where(idx == 0, f, c)
                         for f, c in zip(feed_bb, bb)]
                # fold the microbatch being processed (t - idx during
                # the steady state) into the key so sampling ops draw
                # DIFFERENT noise per microbatch, not one mask reused
                # n_micro times
                mb_key = jax.random.fold_in(
                    key, jnp.clip(t - idx, 0, n_micro - 1))
                out, reds = stage(h_in, bb_in, mb_key)
                # this stage holds a REAL microbatch only during its
                # steady-state window
                mb_valid = jnp.logical_and(t - idx >= 0,
                                           t - idx < n_micro)
                raccs = tuple(a + jnp.where(mb_valid, r, 0)
                              for a, r in zip(raccs, reds))
                slot = t - (n - 1)
                write = jnp.logical_and(
                    idx == n - 1,
                    jnp.logical_and(slot >= 0, slot < n_micro))
                upd = lax.dynamic_update_index_in_dim(
                    outs, out[None], jnp.clip(slot, 0, n_micro - 1), 0)
                outs = jnp.where(write, upd, outs)
                ring = [lax.ppermute(x, axis, perm)
                        for x in [out] + bb_in]
                return (ring[0], ring[1:], outs, raccs), None

            (_, _, outs, raccs), _ = lax.scan(
                tick, (h_init, bb_init, outs0, racc0),
                jnp.arange(total))
            outs = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
            # assemble each family's per-segment values: this stage
            # owns segments [idx*k, (idx+1)*k); microbatch-mean, then
            # psum gathers the other stages' slots
            fulls = []
            for acc in raccs:
                full = _vary(jnp.zeros((k * n,) + acc.shape[1:],
                                       acc.dtype), axis)
                full = lax.dynamic_update_slice_in_dim(
                    full, acc / n_micro, idx * k, 0)
                fulls.append(lax.psum(full, axis))
            return lax.psum(outs, axis), tuple(fulls)

        # manual ONLY over the pp ring axis: 'tp' (if present) stays an
        # auto axis, so GSPMD partitions the segment matmuls inside the
        # ring body by the stacked params' tp shardings — the same
        # composition mechanism as the dp x tp Executor path
        fn = jax.shard_map(
            local, mesh=self.mesh,
            axis_names=frozenset({axis}),
            in_specs=([P(axis)] * len(stacked),
                      P(), [P()] * len(xs_bb),
                      [P()] * len(consts), P()),
            out_specs=(P(), tuple(P() for _ in loop.reduce_outs)))
        ys, fulls = fn(stacked, xs_h, xs_bb, consts, key)
        for fam, arr in zip(loop.reduce_outs, fulls):
            for si, nm in enumerate(fam):
                env[nm] = arr[si]
        return ys.reshape((B,) + ys.shape[2:])

    # ------------------------------------------------------------------
    def _build_step(self, extra_fetches=()):
        if self.schedule == "1f1b":
            from .pipeline_1f1b import build_1f1b_step

            return build_1f1b_step(self, extra_fetches)
        diff_names = [
            n for n in self.params_a
            if jnp.issubdtype(jnp.asarray(self.state[n]).dtype,
                              jnp.floating)]
        nondiff = [n for n in self.state_names if n not in diff_names]
        sections, phase_b = self.sections, self.phase_b
        loss_name, aux_names = self.loss_name, self.aux_names
        state_out = self.state_out

        def loss_fn(diff_params, nondiff_state, feeds, key):
            env = {}
            env.update(nondiff_state)
            env.update(diff_params)
            env.update(feeds)
            cell = [jax.random.fold_in(key, 1)]
            for sec in sections:
                if sec.kind == "repl":
                    for op in sec.ops:
                        run_op(op, env, rng_cell=cell,
                               rng_salt=op._uid)
                else:
                    env[sec.loop.bounds[-1]] = self._run_loop(
                        sec.loop, env, jax.random.fold_in(key, 2))
            aux = {n: env[n] for n in aux_names if n in env}
            for n in state_out:
                if n in env:
                    aux.setdefault(n, env[n])
            # requested fetches that materialize inside the forward
            # (head/tail activations, reduce observables) ride out
            # through aux — XLA dead-codes them when unfetched
            for n in extra_fetches:
                if n in env:
                    aux.setdefault(n, env[n])
            # mean() returns a [1] tensor; grad needs a scalar
            return jnp.reshape(env[loss_name], ()), aux

        def step(state, feeds, rng):
            key, rng_next = jax.random.split(rng)
            diff = {n: state[n] for n in diff_names}
            nond = {n: state[n] for n in nondiff}
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(diff, nond, feeds, key)
            env = dict(state)
            env.update(feeds)
            env.update(aux)
            for n, g in grads.items():
                env[grad_var_name(n)] = g
            cell = [jax.random.fold_in(key, 3)]
            for op in phase_b:
                run_op(op, env, rng_cell=cell, rng_salt=op._uid)
            new_state = dict(state)
            for n in self.state_names:
                if n in env:
                    new_state[n] = env[n]
            fetches = {}
            for n in extra_fetches:
                if n not in env:
                    raise PipelineFetchError(
                        f"fetch target {n!r} is not materialized by "
                        f"the pipeline schedule: it is neither the "
                        f"loss, a persistable, a head/tail-section "
                        f"var, a gradient, nor a loop reduce output. "
                        f"Loop-internal activations are only held "
                        f"per microbatch inside the stage scan.")
                fetches[n] = env[n]
            return new_state, loss, fetches, rng_next

        return step

    # ------------------------------------------------------------------
    def run(self, feed: Dict, fetch_list=None, return_numpy=True):
        """One training step. Returns [loss] (plus any fetched state
        vars named in fetch_list). return_numpy=False keeps the LOSS
        as a device array so steps pipeline without a host round-trip
        (PERF.md "Measurement pitfalls": convert only the last one);
        state fetches are converted regardless because their buffers
        are donated to the next step."""
        if not self.state:
            raise RuntimeError(
                "PipelineTrainer.run before initialize(scope)")
        feeds = {}
        block = self.program.global_block
        for n in self.feed_names:
            if n not in feed:
                raise KeyError(f"missing feed {n!r}")
            v = block._find_var_recursive(n)
            from ..core.types import to_np_dtype
            arr = np.asarray(feed[n])
            want = to_np_dtype(v.dtype) if v is not None and v.dtype \
                else arr.dtype
            if arr.dtype != want and (
                    np.issubdtype(arr.dtype, np.floating)
                    == np.issubdtype(want, np.floating)):
                arr = arr.astype(want)
            feeds[n] = arr
        names = [f.name if hasattr(f, "name") else f
                 for f in (fetch_list or [])]
        extra = tuple(dict.fromkeys(
            n for n in names
            if n != self.loss_name and n not in self.state))
        spec = (tuple(sorted((n, a.shape, str(a.dtype))
                             for n, a in feeds.items())), extra)
        # cache per spec: the periodic-observability pattern (fetch
        # observables every Nth step) alternates fetch sets and must
        # not recompile the whole step on every transition
        if self._jitted is None:
            self._jitted = {}
        jitted = self._jitted.get(spec)
        if jitted is None:
            step = self._build_step(extra_fetches=extra)
            jitted = self._jitted[spec] = jax.jit(
                step, donate_argnums=(0,))
        self.state, loss, fetched, self._rng = jitted(
            self.state, feeds, self._rng)
        out = [np.asarray(loss) if return_numpy else loss]
        for name in names:
            if name == self.loss_name:
                continue
            # state entries are ALWAYS converted: their device buffers
            # are donated to the next step's jit call, so returning
            # the live reference would hand the caller an array that
            # dies on the next run(). Loss and extra fetches are fresh
            # jit outputs, safe to keep on device under
            # return_numpy=False (PERF.md: steps pipeline without a
            # host round-trip).
            if name in self.state:
                out.append(np.asarray(self.state[name]))
            else:
                out.append(np.asarray(fetched[name]) if return_numpy
                           else fetched[name])
        return out


def _vary(x, axis_name):
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    return lax.pvary(x, axis_name)


def _classify_batch_major(block, name, val, B):
    """True when `name` is per-example data (split per microbatch),
    False when it is a broadcast constant — decided by var METADATA
    first, not runtime shape alone: a non-batch var whose leading dim
    coincidentally equals B (e.g. a [T,T] attention mask when
    seq == batch) must NOT be split. Declared -1 leading dim (or a
    data var) = batch-major; a fully concrete declaration whose
    leading dim happens to equal B is AMBIGUOUS and errors with
    guidance rather than silently splitting (wrong numerics) or
    silently broadcasting (also wrong, the other way). Shared by the
    GPipe and 1F1B schedules."""
    runtime_batch = getattr(val, "ndim", 0) >= 1 and val.shape[0] == B
    var = block._find_var_recursive(name)
    decl = tuple(var.shape) if var is not None and var.shape else None
    if decl is not None and len(decl) == getattr(val, "ndim", 0):
        per_batch = runtime_batch and (decl[0] == -1 or var.is_data)
        if runtime_batch and not per_batch:
            raise ValueError(
                f"pipeline: input {name!r} (declared shape {decl}) "
                f"has leading dim == batch {B} but is not declared "
                f"batch-major; cannot tell per-microbatch data from a "
                f"broadcast constant. Declare its batch dim as -1 "
                f"(per-microbatch) or reshape so the leading dim "
                f"differs from the batch (constant).")
        return per_batch
    return runtime_batch


def _fold_salt(uid, seg_idx):
    """Combine op uid with the (possibly traced) segment index so
    sampling ops in different segments draw different noise."""
    return uid + 100003 * seg_idx
