"""Device mesh construction (replaces reference NCCLContextMap
nccl_helper.h:90 device-ring setup with jax.sharding.Mesh topology).

Axes follow the scaling-book convention:
  dp  -- data parallel (batch)
  tp  -- tensor parallel (weight matrices' inner dims)
  sp  -- sequence/context parallel (time dim; ring attention)
  pp  -- pipeline parallel (layer groups)
  ep  -- expert parallel (MoE experts)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

import jax
from jax.sharding import Mesh


AXES = ("dp", "tp", "sp", "pp", "ep")


@dataclass
class MeshConfig:
    dp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    def total(self):
        return self.dp * self.tp * self.sp * self.pp * self.ep

    def axis_sizes(self):
        return {"dp": self.dp, "tp": self.tp, "sp": self.sp,
                "pp": self.pp, "ep": self.ep}


def factorize(n_devices: int, want_tp=True, want_sp=False) -> MeshConfig:
    """Reasonable default factorization of a device count."""
    cfg = MeshConfig()
    n = n_devices
    if want_tp and n % 2 == 0:
        cfg.tp = 2
        n //= 2
    if want_sp and n % 2 == 0:
        cfg.sp = 2
        n //= 2
    cfg.dp = n
    return cfg


def make_mesh(config: Optional[MeshConfig] = None, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if config is None:
        config = MeshConfig(dp=len(devices))
    assert config.total() == len(devices), \
        f"mesh {config} needs {config.total()} devices, have " \
        f"{len(devices)}"
    arr = np.array(devices).reshape(
        config.dp, config.tp, config.sp, config.pp, config.ep)
    return Mesh(arr, AXES)
