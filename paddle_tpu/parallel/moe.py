"""Mixture-of-Experts with expert parallelism over an 'ep' mesh axis.

Beyond-reference capability (SURVEY.md §2.4: expert parallelism ABSENT):
Switch-Transformer-style routing (top-1, Fedus et al. '21) and GShard
top-2 (Lepikhin et al. '20) with fixed expert capacity, the
load-balancing auxiliary loss (Switch eq. 4), experts sharded over
'ep', token dispatch/return as `lax.all_to_all` over ICI -- the
standard TPU MoE dataflow (dispatch einsum -> a2a -> expert FFN -> a2a
-> combine einsum), fully differentiable.

Three entry points:
* `route_tokens` -- router math shared by every path: top-k selection,
  priority-ordered capacity assignment, dispatch/combine tensors, aux
  loss. Pure and mesh-free.
* `moe_apply` / `moe_local` -- the shard_map expert-parallel form.
* the `switch_moe` graph op (ops/nn_ops.py) + `layers.switch_moe` --
  the Program path; inside a `with expert_parallel(mesh):` scope the op
  lowers to the shard_map form, otherwise it runs the identical dense
  math on one device, so ep=N and ep=1 are numerically interchangeable.

Layout contract inside shard_map:
  x_local:  [t, d]            tokens sharded over ep
  wg:       [d, E]            router weights, replicated (E global experts)
  w1_local: [e_local, d, f]   this shard's experts
  w2_local: [e_local, f, d]
Over-capacity tokens are dropped (output zero), matching the canonical
Switch formulation. Combine scaling: raw router prob for top-1
(Switch), probs normalized over the chosen k for k>1 (GShard).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["route_tokens", "moe_local", "moe_apply", "expert_parallel",
           "active_expert_parallel", "moe_dense", "RoutingResult"]


class RoutingResult(NamedTuple):
    """route_tokens output; `drop_frac` is the fraction of valid
    tokens that received ZERO dispatch slots (silent over-capacity
    drops are the first thing to monitor in real MoE training)."""
    dispatch: jax.Array     # [t, E, C] 0/1
    combine: jax.Array      # [t, E, C] float weights
    aux: jax.Array          # scalar, Switch eq. 4
    gates: jax.Array        # [t, E]
    drop_frac: jax.Array    # scalar in [0, 1]


def route_tokens(x, wg, capacity: int, top_k: int = 1, mask=None,
                 n_real_experts: int = None):
    """Router + capacity assignment.

    x: [t, d]; wg: [d, E]. Returns a RoutingResult with dispatch
    [t,E,C] 0/1, combine [t,E,C] float weights, aux_loss scalar,
    gates [t,E], and drop_frac — the fraction of (valid) tokens with
    ZERO dispatch slots, the first thing to monitor in real MoE
    training (silent over-capacity drops).

    `mask` ([t] 0/1, optional) marks valid tokens: padding rows (the
    divisibility fallback in moe_apply) neither claim capacity nor
    perturb the aux statistics. `n_real_experts` marks trailing expert
    columns as padding: their logits are masked to -inf (so no token
    routes there) and the aux coefficient uses the real count.

    Capacity is assigned in choice-priority order (every token's first
    choice before any second choice -- the GShard ordering), each
    choice FIFO by token index. The aux loss is Switch eq. 4:
    E * sum_e f_e * P_e with f_e the fraction of tokens whose PRIMARY
    choice is e and P_e the mean router probability of e; it is 1.0 at
    perfect balance and rises as routing collapses.
    """
    t, d = x.shape
    E = wg.shape[-1]
    C = capacity
    logits = (x.astype(jnp.float32) @ wg.astype(jnp.float32))
    if n_real_experts is not None and n_real_experts < E:
        # pad-expert columns: masked AFTER the matmul (baking -inf
        # into wg would flip sign with negative activations)
        col_ok = jnp.arange(E) < n_real_experts
        logits = jnp.where(col_ok[None, :], logits, -jnp.inf)
    gates = jax.nn.softmax(logits, axis=-1)              # [t, E]
    gval, gidx = lax.top_k(gates, top_k)                 # [t, k]
    if top_k > 1:
        scale = gval / jnp.maximum(
            gval.sum(-1, keepdims=True), 1e-9)
    else:
        scale = gval                                     # Switch: raw p
    valid = jnp.ones((t,), jnp.float32) if mask is None \
        else mask.astype(jnp.float32)
    n_valid = jnp.maximum(valid.sum(), 1.0)

    dispatch = jnp.zeros((t, E, C), jnp.float32)
    combine = jnp.zeros((t, E, C), jnp.float32)
    counts = jnp.zeros((E,), jnp.float32)
    for j in range(top_k):
        oh = jax.nn.one_hot(gidx[:, j], E,
                            dtype=jnp.float32) * valid[:, None]
        pos = (jnp.cumsum(oh, axis=0) - 1.0) * oh + counts[None, :] * oh
        keep = (pos < C) & (oh > 0)
        posC = jax.nn.one_hot(pos.astype(jnp.int32), C,
                              dtype=jnp.float32)
        sel = posC * keep[..., None]
        dispatch = dispatch + sel
        combine = combine + sel * scale[:, j][:, None, None]
        counts = counts + (oh * keep).sum(0)

    prim_sum, gate_sum, dropped_sum, _ = _routing_stats(
        gates, dispatch, valid)
    f = prim_sum / n_valid
    p = gate_sum / n_valid
    aux = float(n_real_experts or E) * jnp.sum(f * p)
    drop_frac = dropped_sum / n_valid
    return RoutingResult(dispatch, combine, aux, gates, drop_frac)


def _routing_stats(gates, dispatch, valid):
    """Local NUMERATORS of the Switch routing statistics — the one
    definition shared by route_tokens (local means) and moe_local
    (psum-weighted global means): primary-choice counts per expert,
    gate mass per expert, dropped-token count (a valid token whose
    dispatch has no slot in ANY choice), valid-token count."""
    prim = jax.nn.one_hot(jnp.argmax(gates, -1), gates.shape[-1],
                          dtype=jnp.float32) * valid[:, None]
    dropped = (dispatch.sum((1, 2)) < 0.5) * valid
    return (prim.sum(0), (gates * valid[:, None]).sum(0),
            dropped.sum(), valid.sum())


def moe_dense(x, wg, w1, w2, capacity: int, top_k: int = 1):
    """Single-device MoE forward with the SAME routing/capacity math
    as the expert-parallel form (used by the `switch_moe` op outside an
    expert_parallel scope). x: [t, d].
    Returns (out [t, d], aux, drop_frac)."""
    r = route_tokens(x, wg, capacity, top_k)
    # router math stays fp32 (route_tokens); the expert FFN — the
    # dominant FLOPs — runs in the input dtype so bf16/AMP models keep
    # their MXU precision
    dispatch = r.dispatch.astype(x.dtype)
    xs = jnp.einsum("tec,td->ecd", dispatch, x)          # [E, C, d]
    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", xs, w1.astype(x.dtype)))
    y = jnp.einsum("ecf,efd->ecd", h, w2.astype(x.dtype))
    out = jnp.einsum("ecd,tec->td", y, r.combine.astype(x.dtype))
    return out, r.aux, r.drop_frac


def moe_local(x, wg, w1, w2, axis_name: str, capacity: int,
              top_k: int = 1, mask=None, n_real_experts: int = None):
    """shard_map body. Returns (out_local [t, d], aux scalar
    replicated, drop_frac scalar replicated). Aux/drop statistics are
    psum-weighted over shards so the values equal the global-batch
    formulas even when padding rows make shards unevenly valid."""
    n = lax.psum(1, axis_name)
    t, d = x.shape
    e_local = w1.shape[0]
    E = e_local * n
    C = capacity
    E_real = int(n_real_experts or E)

    r = route_tokens(x, wg, C, top_k, mask=mask,
                     n_real_experts=E_real)
    dispatch, combine, gates = r.dispatch, r.combine, r.gates
    valid = jnp.ones((t,), jnp.float32) if mask is None \
        else mask.astype(jnp.float32)
    # global aux/drop: psum the SAME local numerators route_tokens
    # uses (_routing_stats), then divide by the global valid count
    prim_sum, gate_sum, dropped_sum, valid_sum = _routing_stats(
        gates, dispatch, valid)
    n_valid = jnp.maximum(lax.psum(valid_sum, axis_name), 1.0)
    f = lax.psum(prim_sum, axis_name) / n_valid
    p = lax.psum(gate_sum, axis_name) / n_valid
    aux = E_real * jnp.sum(f * p)
    drop_frac = lax.psum(dropped_sum, axis_name) / n_valid

    # expert FFN in the input dtype (router stays fp32; see moe_dense)
    xs = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    # scatter expert groups to their owner shards; gather this shard's
    # experts' tokens from every shard: [E, C, d] -> [e_local, n*C, d]
    recv = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)
    h = jax.nn.relu(jnp.einsum("ekd,edf->ekf", recv,
                               w1.astype(x.dtype)))
    y = jnp.einsum("ekf,efd->ekd", h, w2.astype(x.dtype))
    # route results back: [e_local, n*C, d] -> [E, C, d]
    back = lax.all_to_all(y, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)
    out = jnp.einsum("ecd,tec->td", back, combine.astype(x.dtype))
    return out, aux, drop_frac


def moe_apply(x, wg, w1, w2, mesh: Mesh, axis: str = "ep",
              capacity_factor: float = 2.0, top_k: int = 1):
    """x: [tokens, d] global; wg: [d, E]; w1: [E, d, f]; w2: [E, f, d].
    Tokens and experts are sharded over `axis`; returns
    (out [tokens, d], aux_loss scalar, drop_frac scalar).

    Token/expert counts that do NOT divide the ep axis are handled by
    padding (VERDICT r3 weak #5: no hard assert): pad tokens are
    masked out of routing (no capacity claim, no aux/drop effect); pad
    experts get -inf router columns and zero weights, and the aux
    coefficient keeps the REAL expert count."""
    n = mesh.shape[axis]
    t, E = x.shape[0], w1.shape[0]
    t_pad = (-t) % n
    e_pad = (-E) % n
    mask = None
    if t_pad:
        x = jnp.concatenate(
            [x, jnp.zeros((t_pad,) + x.shape[1:], x.dtype)])
        mask = jnp.concatenate([jnp.ones((t,), jnp.float32),
                                jnp.zeros((t_pad,), jnp.float32)])
    if e_pad:
        # zero router columns; route_tokens masks pad-expert LOGITS to
        # -inf itself (n_real_experts) — baking a large negative into
        # wg would flip sign under negative activations
        wg = jnp.concatenate(
            [wg, jnp.zeros((wg.shape[0], e_pad), wg.dtype)], 1)
        w1 = jnp.concatenate(
            [w1, jnp.zeros((e_pad,) + w1.shape[1:], w1.dtype)])
        w2 = jnp.concatenate(
            [w2, jnp.zeros((e_pad,) + w2.shape[1:], w2.dtype)])
    tt, EE = x.shape[0], w1.shape[0]
    # capacity from the PADDED per-shard token count (tt // n == the
    # real tokens a full shard holds) over the REAL expert count —
    # floor(t/n) would shrink real tokens' slots exactly when padding
    # kicks in
    cap = max(1, int(capacity_factor * top_k * (tt // max(1, n)) / E))
    body = functools.partial(moe_local, axis_name=axis, capacity=cap,
                             top_k=top_k, n_real_experts=E)
    in_specs = (P(axis), P(), P(axis), P(axis))
    if mask is not None:
        body_ = body
        body = lambda x_, wg_, w1_, w2_, m_: body_(
            x_, wg_, w1_, w2_, mask=m_)
        in_specs = in_specs + (P(axis),)
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs,
        out_specs=(P(axis), P(), P()))
    put = lambda a, s: jax.device_put(a, NamedSharding(mesh, s))
    args = [put(x, P(axis)), put(wg, P()), put(w1, P(axis)),
            put(w2, P(axis))]
    if mask is not None:
        args.append(put(mask, P(axis)))
    out, aux, drop = fn(*args)
    if t_pad:
        out = out[:t]
    return out, aux, drop


# --- expert-parallel activation scope --------------------------------------
# The `switch_moe` op (ops/nn_ops.py) consults this the same way the
# attention op consults context_parallel: inside the scope, eligible MoE
# ops lower to the shard_map expert-parallel dataflow over the given
# mesh axis; outside it they run moe_dense on one device.
_ACTIVE_EP = None


class expert_parallel:
    """`with expert_parallel(mesh, axis='ep'):` -- route framework
    switch_moe ops through the all_to_all expert-parallel dataflow."""

    def __init__(self, mesh: Mesh, axis: str = "ep"):
        self.cfg = (mesh, axis)

    def __enter__(self):
        global _ACTIVE_EP
        self._prev = _ACTIVE_EP
        _ACTIVE_EP = self.cfg
        return self

    def __exit__(self, *a):
        global _ACTIVE_EP
        _ACTIVE_EP = self._prev


def active_expert_parallel():
    return _ACTIVE_EP


def ep_applicable(n_tokens: int, n_experts: int) -> bool:
    # divisibility no longer gates EP: moe_apply pads tokens/experts
    # to the axis size and masks the padding out of routing/statistics
    if _ACTIVE_EP is None:
        return False
    mesh, axis = _ACTIVE_EP
    return mesh.shape[axis] > 1


def dryrun(n_devices: int) -> None:
    """Driver smoke: EP MoE vs dense per-token expert application (big
    capacity so nothing drops), top-1 and top-2."""
    import numpy as np

    from .mesh import make_mesh, MeshConfig

    ep = 2 if n_devices % 2 == 0 else 1
    if ep == 1:
        print("dryrun ep: skipped (odd device count)")
        return
    mesh = make_mesh(MeshConfig(ep=ep), devices=jax.devices()[:ep])

    t, d, f, E = 16, 8, 16, 4
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(t, d).astype(np.float32))
    wg = jnp.asarray(r.randn(d, E).astype(np.float32))
    w1 = jnp.asarray(r.randn(E, d, f).astype(np.float32) * 0.3)
    w2 = jnp.asarray(r.randn(E, f, d).astype(np.float32) * 0.3)

    got, aux, drop = moe_apply(x, wg, w1, w2, mesh,
                               capacity_factor=float(E * 2))
    assert float(drop) == 0.0, f"unexpected drops: {drop}"
    gates = jax.nn.softmax(x @ wg, axis=-1)
    idx = jnp.argmax(gates, axis=-1)
    want = jnp.stack([
        gates[i, idx[i]] * (jax.nn.relu(x[i] @ w1[idx[i]]) @ w2[idx[i]])
        for i in range(t)])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-4)
    assert np.isfinite(float(aux)) and float(aux) >= 1.0 - 1e-5

    # top-2 EP must match the dense path exactly
    got2, aux2, _ = moe_apply(x, wg, w1, w2, mesh,
                              capacity_factor=float(E * 2), top_k=2)
    want2, auxd, _ = moe_dense(x, wg, w1, w2,
                               capacity=t * 2, top_k=2)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(float(aux2), float(auxd), rtol=1e-5)
    print(f"dryrun ep: {ep}-shard expert-parallel MoE matches dense "
          f"(top-1 and top-2) ok")
