"""Mixture-of-Experts with expert parallelism over an 'ep' mesh axis.

Beyond-reference capability (SURVEY.md §2.4: expert parallelism ABSENT):
Switch-Transformer-style top-1 routing with fixed expert capacity,
experts sharded over 'ep', token dispatch/return as `lax.all_to_all`
over ICI -- the standard TPU MoE dataflow (dispatch einsum -> a2a ->
expert FFN -> a2a -> combine einsum), fully differentiable.

Layout contract inside shard_map:
  x_local:  [t, d]            tokens sharded over ep
  wg:       [d, E]            router weights, replicated (E global experts)
  w1_local: [e_local, d, f]   this shard's experts
  w2_local: [e_local, f, d]
Over-capacity tokens are dropped (output zero), matching the canonical
Switch formulation.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def moe_local(x, wg, w1, w2, axis_name: str, capacity: int):
    n = lax.psum(1, axis_name)
    t, d = x.shape
    e_local = w1.shape[0]
    E = e_local * n
    C = capacity

    logits = x @ wg                                     # [t, E]
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_val = gates.max(axis=-1)                       # [t]
    expert = gates.argmax(axis=-1)                      # [t]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot   # position in expert
    keep = (pos < C) & (onehot > 0)
    # dispatch tensor [t, E, C]
    posC = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=jnp.float32)
    dispatch = posC * keep[..., None]
    xs = jnp.einsum("tec,td->ecd", dispatch,
                    x.astype(jnp.float32))              # [E, C, d]
    # scatter expert groups to their owner shards; gather this shard's
    # experts' tokens from every shard: [E, C, d] -> [e_local, n*C, d]
    recv = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)
    h = jax.nn.relu(jnp.einsum("ekd,edf->ekf", recv,
                               w1.astype(jnp.float32)))
    y = jnp.einsum("ekf,efd->ekd", h, w2.astype(jnp.float32))
    # route results back: [e_local, n*C, d] -> [E, C, d]
    back = lax.all_to_all(y, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)
    combine = dispatch * gate_val[:, None, None]
    out = jnp.einsum("ecd,tec->td", back, combine)
    return out.astype(x.dtype)


def moe_apply(x, wg, w1, w2, mesh: Mesh, axis: str = "ep",
              capacity_factor: float = 2.0):
    """x: [tokens, d] global; wg: [d, E]; w1: [E, d, f]; w2: [E, f, d].
    Tokens and experts are sharded over `axis`; returns [tokens, d]."""
    n = mesh.shape[axis]
    t, E = x.shape[0], w1.shape[0]
    assert t % n == 0 and E % n == 0, \
        f"tokens({t}) and experts({E}) must divide ep({n})"
    cap = max(1, int(capacity_factor * (t // n) / E))
    body = functools.partial(moe_local, axis_name=axis, capacity=cap)
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(axis)),
        out_specs=P(axis))
    put = lambda a, s: jax.device_put(a, NamedSharding(mesh, s))
    return fn(put(x, P(axis)), put(wg, P()), put(w1, P(axis)),
              put(w2, P(axis)))


def dryrun(n_devices: int) -> None:
    """Driver smoke: EP MoE vs dense per-token expert application (big
    capacity so nothing drops)."""
    import numpy as np

    from .mesh import make_mesh, MeshConfig

    ep = 2 if n_devices % 2 == 0 else 1
    if ep == 1:
        print("dryrun ep: skipped (odd device count)")
        return
    mesh = make_mesh(MeshConfig(ep=ep), devices=jax.devices()[:ep])

    t, d, f, E = 16, 8, 16, 4
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(t, d).astype(np.float32))
    wg = jnp.asarray(r.randn(d, E).astype(np.float32))
    w1 = jnp.asarray(r.randn(E, d, f).astype(np.float32) * 0.3)
    w2 = jnp.asarray(r.randn(E, f, d).astype(np.float32) * 0.3)

    got = moe_apply(x, wg, w1, w2, mesh, capacity_factor=float(E * 2))

    gates = jax.nn.softmax(x @ wg, axis=-1)
    idx = jnp.argmax(gates, axis=-1)
    want = jnp.stack([
        gates[i, idx[i]] * (jax.nn.relu(x[i] @ w1[idx[i]]) @ w2[idx[i]])
        for i in range(t)])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-4)
    print(f"dryrun ep: {ep}-shard expert-parallel MoE matches dense ok")
