"""1F1B (one-forward-one-backward) pipeline schedule.

GPipe (pipeline_program.py) differentiates the whole forward ring with
outer AD, so every microbatch's stage residuals stay live until the
backward phase begins: peak activation memory grows with ``n_micro``.
The 1F1B schedule (PipeDream-flush — the schedule Megatron-LM uses)
interleaves each microbatch's backward as soon as the last stage
finishes its forward, so a stage holds at most ``pp - stage_idx``
in-flight microbatches regardless of ``n_micro``.

Reference precedent: Fluid has no pipeline engine (SURVEY.md §2.4); the
closest reference artifact is the batch-merge pass
(/root/reference/paddle/fluid/framework/ir/multi_batch_merge_pass.cc:1)
which replicates a block per sub-batch and accumulates grads — the
memory/schedule tradeoff this module manages explicitly.

TPU-native design
-----------------
Outer AD cannot express 1F1B (JAX runs the whole forward before any
backward), so this engine drives AD *manually*, stage by stage:

* the pre-loop ("head") ops run ONCE over the full batch under
  ``jax.vjp``, outside the ring;
* the loop body and the post-loop ("tail", which produces the loss)
  run inside ONE ``shard_map``-over-'pp' ``lax.scan`` whose tick ``t``
  makes stage ``i`` run
    - forward  of microbatch ``m = (t - i) / 2``               (when integral)
    - backward of microbatch ``m = (t - (2*pp - 1 - i)) / 2``  (when integral)
  — the two parities are disjoint, so each tick is one F or one B,
  selected with ``lax.cond`` (no collectives inside the branches);
* a forward tick stashes only the stage INPUT (circular buffer of
  ``min(pp, n_micro)`` slots); the backward tick re-runs the stage
  under ``jax.vjp`` (stage-granular rematerialisation) with the SAME
  rng derivation as the forward tick, so recomputed dropout masks
  match bit-for-bit;
* activations ride a forward ``ppermute`` ring, cotangents ride a
  reverse ring; the last stage runs the tail per microbatch inside its
  backward tick and seeds the cotangent chain with ``1/n_micro``;
* stacked per-segment params are sharded over 'pp' (same layout as
  GPipe); their grads come back sharded the same way, and 'tp' axes
  stay AUTO inside the ring (GSPMD partitions the segment matmuls),
  exactly like the GPipe path.

Scheduling formulas (0-based stage ``i``, microbatch ``m``)::

    F(i, m) = i + 2*m
    B(i, m) = 2*pp - 1 + 2*m - i        # last stage: B = F + 1
    ticks   = 2 * (n_micro + pp - 1)

In-flight microbatches at stage ``i``: at most ``pp - i`` (vs
``n_micro`` for GPipe) — the stashed-activation win that
tests/test_pipeline_1f1b.py proves via ``compiled.memory_analysis()``.

Composition: 'dp' works (auto axis; nothing sharded forces a
collective inside the divergent per-stage ``lax.cond``); 'tp' does
NOT — tp-sharded params make GSPMD insert tp collectives inside the
branches, and devices at different pp coordinates then disagree on
the collective sequence and deadlock (observed on the 8-dev mesh).
tp meshes get a named error pointing at GPipe.

Semantics caveat (microbatched reduce outputs): the tail runs per
microbatch, so a loop reduce output enters the loss as
``mean_m f(red_m)`` where GPipe computes ``f(mean_m red_m)``. The two
agree exactly when the tail is LINEAR in the reduce outputs (true for
the Switch aux-loss pattern: the aux enters the cost as a scaled sum);
a tail that is nonlinear in a reduce output (e.g. a z-loss squaring a
router statistic) trains to a slightly different objective under
'1f1b' than under 'gpipe' — same direction of difference as GPipe
itself vs the unmicrobatched Executor. Nonlinearity is undecidable
from the op list, so this is documented rather than guarded.
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.program import grad_var_name
from ..core.registry import EMPTY_VAR, run_op
from .pipeline_program import (PipelinePartitionError,
                               _classify_batch_major, _op_reads,
                               _op_writes, _persistable, _vary)

__all__ = ["build_1f1b_step"]


def build_1f1b_step(tr, extra_fetches=()):
    """Build ``step(state, feeds, rng) -> (new_state, loss, fetches,
    rng_next)`` running ``tr``'s program under the 1F1B schedule.
    ``tr`` is a PipelineTrainer constructed with ``schedule='1f1b'``;
    ``extra_fetches`` names non-state vars to materialize (head
    outputs, gradients, reduce observables — NOT per-microbatch tail
    activations, which only GPipe holds at full batch)."""
    if tr.pp <= 1:
        raise PipelinePartitionError(
            "schedule='1f1b' needs a 'pp' mesh axis > 1 (with pp == 1 "
            "the loop is a plain lax.scan and GPipe/1F1B are the same "
            "program; use schedule='gpipe')")
    if tr.tp > 1:
        # The IR-level form of this trap is now PROVABLE instead of
        # hand-rejected: the per-stage F/B predicates are exactly the
        # "pp_stage_id" divergence source in the absint seed table
        # (analysis/absint.py), and a collective/sharding annotation
        # under such a predicate is PTA130/131 at ERROR. This named
        # rejection stays as the jax-level belt-and-braces for THIS
        # engine, whose schedule never goes through the Program IR.
        from ..analysis import absint as _absint

        assert "pp_stage_id" in _absint.divergence_sources(), \
            "absint seed table lost the pp_stage_id divergence " \
            "source the 1F1B rejection is grounded in"
        raise PipelinePartitionError(
            "schedule='1f1b' does not compose with tp: the schedule "
            "selects F/B work per stage with lax.cond, and tp-sharded "
            "params force GSPMD to insert tp collectives INSIDE the "
            "divergent branches — devices at different pp coordinates "
            "then disagree on the collective sequence and deadlock "
            "(observed on the 8-dev CPU mesh; the Program-IR form of "
            "this trap is checker PTA130/131's proof domain). Use "
            "schedule='gpipe' for pp x tp meshes ('dp' composes "
            "fine: nothing sharded forces a branch-internal "
            "collective).")
    loop_secs = [s for s in tr.sections if s.kind == "loop"]
    if len(loop_secs) != 1:
        raise PipelinePartitionError(
            f"schedule='1f1b' supports exactly one pipelined loop "
            f"(got {len(loop_secs)}; multi-stack programs such as "
            f"encoder+decoder need schedule='gpipe')")
    loop = loop_secs[0].loop
    li = tr.sections.index(loop_secs[0])
    head_ops = [op for s in tr.sections[:li] for op in s.ops]
    tail_ops = [op for s in tr.sections[li + 1:] for op in s.ops]

    block = tr.program.global_block
    loop_param_names = {n for seg in loop.seg_params for n in seg}
    red_names = {nm for fam in loop.reduce_outs for nm in fam}
    h_final_name = loop.bounds[-1]

    def persistable(n):
        return _persistable(block, n)

    def is_data(n):
        v = block._find_var_recursive(n)
        return v is not None and v.is_data

    # ---- head/tail variable roles -----------------------------------
    head_writes_set = set()
    for op in head_ops:
        for n in _op_reads(op):
            if n in loop_param_names:
                raise PipelinePartitionError(
                    f"1f1b: head op {op.type!r} reads loop param "
                    f"{n!r}; params shared between the loop and the "
                    f"head are not supported")
        head_writes_set.update(_op_writes(op))

    tail_params = []
    tail_writes = set()
    tail_ext = []          # non-persistable externals the tail reads
    for op in tail_ops:
        for n in _op_reads(op):
            if n == EMPTY_VAR:
                continue
            if n in loop_param_names:
                raise PipelinePartitionError(
                    f"1f1b: tail op {op.type!r} reads loop param "
                    f"{n!r}; params shared between the loop and the "
                    f"tail are not supported")
            if persistable(n):
                if n not in tail_params:
                    tail_params.append(n)
            elif n not in tail_writes and n not in tail_ext:
                tail_ext.append(n)
        tail_writes.update(_op_writes(op))

    tail_ext_nonred = []
    for n in tail_ext:
        if n == h_final_name or n in red_names:
            continue
        if not (is_data(n) or n in head_writes_set):
            raise PipelinePartitionError(
                f"1f1b: tail reads {n!r}, which is neither a data "
                f"var, a head output, the loop output, nor a loop "
                f"reduce output")
        tail_ext_nonred.append(n)
    for n in loop.bcast:
        if not (is_data(n) or n in head_writes_set):
            raise PipelinePartitionError(
                f"1f1b: loop broadcast input {n!r} is neither a data "
                f"var nor a head output")

    # ---- phase-B aux closure (lr schedules etc.) --------------------
    # tail ops computable WITHOUT pipelined activations (reduce
    # observables count as available: the ring reassembles them),
    # needed to produce aux/state_out values that phase B reads
    aux_avail = set(tr.state_names) | set(tr.feed_names) \
        | head_writes_set | red_names
    aux_ops = []
    for op in tail_ops:
        reads = [n for n in _op_reads(op) if n != EMPTY_VAR]
        if all(n in aux_avail for n in reads):
            aux_ops.append(op)
            aux_avail.update(_op_writes(op))
    for n in list(tr.aux_names) + [x for x in tr.state_out
                                   if x in tail_writes]:
        if n in tail_writes and n not in aux_avail:
            raise PipelinePartitionError(
                f"1f1b: optimizer-phase input {n!r} is computed from "
                f"pipelined activations in the tail; run it through "
                f"schedule='gpipe' instead")

    diff_names = [
        n for n in tr.params_a
        if jnp.issubdtype(jnp.asarray(tr.state[n]).dtype,
                          jnp.floating)]
    for n in sorted(loop_param_names):
        if n not in diff_names:
            raise PipelinePartitionError(
                f"1f1b: loop param {n!r} is not a floating-point "
                f"trainable; the manual-vjp schedule differentiates "
                f"every stacked loop param")
    outer_diff = [n for n in diff_names if n not in loop_param_names]
    nondiff = [n for n in tr.state_names if n not in diff_names]
    tail_nondiff_names = [n for n in tail_params if n not in diff_names]

    n_seg = len(loop.segments)
    pp, axis, n_micro = tr.pp, tr.axis, tr.n_micro
    k = n_seg // pp
    S = min(pp, n_micro)
    loss_name = tr.loss_name
    outside_writes = set(head_writes_set)
    for op in aux_ops:
        outside_writes.update(_op_writes(op))

    # ------------------------------------------------------------------
    def head_apply(diff_params, env_base, key):
        """Run head ops over the full batch; returns the env."""
        env = dict(env_base)
        env.update(diff_params)
        cell = [jax.random.fold_in(key, 1)]
        for op in head_ops:
            run_op(op, env, rng_cell=cell, rng_salt=op._uid)
        return env

    def tail_apply(tail_diff, h_final, red_vals, dconsts, ndconsts,
                   mb_feeds, key, m):
        """Run tail ops on ONE microbatch; returns the scalar loss."""
        env = {}
        env.update(ndconsts)
        env.update(dconsts)
        env.update(tail_diff)
        env.update(mb_feeds)
        env[h_final_name] = h_final
        for fam, buf in zip(loop.reduce_outs, red_vals):
            for si, nm in enumerate(fam):
                env[nm] = buf[si]
        cell = [jax.random.fold_in(jax.random.fold_in(key, 4), m)]
        for op in tail_ops:
            run_op(op, env, rng_cell=cell, rng_salt=op._uid)
        return jnp.reshape(env[loss_name], ())

    # ------------------------------------------------------------------
    def step(state, feeds, rng):
        key, rng_next = jax.random.split(rng)
        diff = {n: state[n] for n in diff_names}
        nond = {n: state[n] for n in nondiff}
        outer = {n: diff[n] for n in outer_diff}

        env_base = {}
        env_base.update(nond)
        env_base.update(feeds)

        # ---- head: full batch, vjp over the non-loop params ---------
        out_names = [n for n in ([loop.bounds[0]] + loop.bcast +
                                 tail_ext_nonred)
                     if n in head_writes_set]
        out_names = list(dict.fromkeys(out_names))

        def head_outs(p):
            env = head_apply(p, env_base, key)
            return tuple(env[n] for n in out_names), env

        if head_ops:
            head_vals, head_vjp, head_env = jax.vjp(
                head_outs, outer, has_aux=True)
            env = dict(head_env)
        else:
            head_vals, head_vjp = (), None
            env = dict(env_base)
            env.update(outer)
        hv = dict(zip(out_names, head_vals))

        def lookup(n):
            return hv[n] if n in hv else env[n]

        h0 = lookup(loop.bounds[0])
        B = h0.shape[0]
        if B % n_micro:
            raise ValueError(
                f"batch {B} not divisible by n_micro {n_micro}")
        mb = B // n_micro

        # ---- classify ring-side inputs ------------------------------
        bb_names, const_names = [], []
        for n in loop.bcast:
            (bb_names if _classify_batch_major(block, n, lookup(n), B)
             else const_names).append(n)
        t_mb, t_const = [], []
        for n in tail_ext_nonred:
            (t_mb if _classify_batch_major(block, n, lookup(n), B)
             else t_const).append(n)
        for n in t_mb:
            if n in head_writes_set:
                raise PipelinePartitionError(
                    f"1f1b: tail reads head-produced batch-major var "
                    f"{n!r}; per-microbatch tail grads are only "
                    f"supported for data vars — use schedule='gpipe'")
        dconst_names = sorted({
            n for n in const_names + t_const
            if n in head_writes_set and jnp.issubdtype(
                jnp.asarray(lookup(n)).dtype, jnp.floating)})
        ndconst_loop = {n: lookup(n) for n in const_names
                        if n not in dconst_names}
        ndconst_tail = {n: lookup(n) for n in t_const
                        if n not in dconst_names}
        for n in tail_nondiff_names:
            ndconst_tail[n] = state[n]
        dconsts = {n: lookup(n) for n in dconst_names}

        # 'dp' is an AUTO axis (like 'tp'): batch rows sharded over it,
        # GSPMD partitions the ring-body compute and inserts the grad
        # reductions (tr._dp_shard is a no-op at dp == 1)
        xs_h = tr._dp_shard(
            h0.reshape((n_micro, mb) + h0.shape[1:]), 1)
        xs_bb = {n: tr._dp_shard(lookup(n).reshape(
            (n_micro, mb) + lookup(n).shape[1:]), 1) for n in bb_names}
        xs_tail = {n: tr._dp_shard(lookup(n).reshape(
            (n_micro, mb) + lookup(n).shape[1:]), 1) for n in t_mb}

        # ---- stack per-segment params (same layout as GPipe) --------
        stacked = []
        for pos in range(len(loop.canon_params)):
            leaves = [diff[loop.seg_params[s][pos]]
                      for s in range(n_seg)]
            st = jnp.stack(leaves)
            st = lax.with_sharding_constraint(
                st, NamedSharding(
                    tr.mesh,
                    tr._stack_spec(loop, pos, leaves[0].shape)))
            stacked.append(st)
        tail_diff = {n: diff[n] for n in tail_params
                     if n in diff_names}
        loop_key = jax.random.fold_in(key, 2)
        T = 2 * (n_micro + pp - 1)

        # reduce-out family shapes (one segment's contribution)
        seg0_params = [diff[n] for n in loop.canon_params]
        probe_bc = {n: (xs_bb[n][0] if n in bb_names else lookup(n))
                    for n in loop.bcast}
        red_sds = jax.eval_shape(
            lambda p, h, bc, kk: tr._seg_apply(loop, p, h, bc, kk, 0)[1],
            seg0_params, xs_h[0], probe_bc, loop_key)
        for sd in red_sds:
            if not jnp.issubdtype(sd.dtype, jnp.floating):
                raise PipelinePartitionError(
                    f"1f1b: a loop reduce output has non-float dtype "
                    f"{sd.dtype}; the manual-vjp schedule carries "
                    f"reduce cotangents and needs float reduce "
                    f"outputs — use schedule='gpipe'")
        red_protos = tuple(
            jnp.zeros((n_seg,) + sd.shape, sd.dtype) for sd in red_sds)

        def stage_fwd(stk_params, h, bb, dcs_, loop_key_, m, idx):
            """This stage's k segments on one microbatch. Returns
            (h_out, per-family [k, ...] reduce outputs). rng
            derivation matches the GPipe path's `stage`
            (pipeline_program.py:736) bit-for-bit, so the backward
            tick's recompute — and GPipe↔1F1B parity — reproduce the
            same noise."""
            bc = dict(ndconst_loop)
            bc.update(dcs_)
            bc.update(bb)
            mb_key = jax.random.fold_in(loop_key_, m)

            def seg_body(hc, xs):
                params, j = xs
                out, reds = tr._seg_apply(loop, params, hc, bc,
                                          mb_key, idx * k + j)
                return out.astype(hc.dtype), reds

            return lax.scan(seg_body, h,
                            (tuple(stk_params), jnp.arange(k)))

        # ---- the 1F1B ring ------------------------------------------
        def ring(stk, tail_d, dcs, key_):
            # tail_d/dcs arrive replicated (in_spec P()); differentiate
            # them as VARYING values — the transpose of the implicit
            # replicated->varying cast is a psum, and a collective
            # inside the divergent per-stage lax.cond would deadlock.
            # The masked psum after the scan does the cross-stage
            # reduction instead.
            tail_d = jax.tree.map(lambda x: _vary(x, axis), tail_d)
            dcs = jax.tree.map(lambda x: _vary(x, axis), dcs)
            idx = lax.axis_index(axis)
            fwd_perm = [(i, i + 1) for i in range(pp - 1)]
            bwd_perm = [(i, i - 1) for i in range(1, pp)]

            def pick(buf, m):
                return lax.dynamic_index_in_dim(
                    buf, jnp.clip(m, 0, n_micro - 1), keepdims=False)

            def zv(shape, dtype):
                return _vary(jnp.zeros(shape, dtype), axis)

            h_sd = jax.eval_shape(lambda: xs_h[0])
            carry0 = dict(
                ring_h=zv(h_sd.shape, h_sd.dtype),
                ring_bb={n: zv(xs_bb[n][0].shape, xs_bb[n].dtype)
                         for n in bb_names},
                ring_red=tuple(zv(r.shape, r.dtype)
                               for r in red_protos),
                ring_gh=zv(h_sd.shape, h_sd.dtype),
                ring_gbb={n: zv(xs_bb[n][0].shape, xs_bb[n].dtype)
                          for n in bb_names},
                ring_gred=tuple(zv(r.shape, r.dtype)
                                for r in red_protos),
                stash_h=zv((S,) + h_sd.shape, h_sd.dtype),
                stash_bb={n: zv((S,) + xs_bb[n][0].shape,
                                xs_bb[n].dtype) for n in bb_names},
                stash_red=tuple(zv((S,) + r.shape, r.dtype)
                                for r in red_protos),
                acc_gstk=[jnp.zeros_like(s) for s in stk],
                acc_gtail=jax.tree.map(jnp.zeros_like, tail_d),
                acc_gdc=jax.tree.map(jnp.zeros_like, dcs),
                buf_gh0=zv((n_micro,) + h_sd.shape, h_sd.dtype),
                buf_gbb={n: zv((n_micro,) + xs_bb[n][0].shape,
                               xs_bb[n].dtype) for n in bb_names},
                acc_loss=_vary(jnp.zeros((), jnp.float32), axis),
                acc_red=tuple(zv(r.shape, r.dtype)
                              for r in red_protos),
            )

            def zero_sends(c):
                return dict(
                    h=jnp.zeros_like(c["ring_h"]),
                    bb={n: jnp.zeros_like(c["ring_bb"][n])
                        for n in bb_names},
                    red=tuple(jnp.zeros_like(r)
                              for r in c["ring_red"]),
                    gh=jnp.zeros_like(c["ring_gh"]),
                    gbb={n: jnp.zeros_like(c["ring_gbb"][n])
                         for n in bb_names},
                    gred=tuple(jnp.zeros_like(r)
                               for r in c["ring_gred"]))

            def f_branch(c, t):
                m = (t - idx) // 2
                is0 = idx == 0
                h_in = jnp.where(is0, pick(xs_h, m), c["ring_h"])
                bb_in = {n: jnp.where(is0, pick(xs_bb[n], m),
                                      c["ring_bb"][n])
                         for n in bb_names}
                red_in = tuple(
                    jnp.where(is0, jnp.zeros_like(r), r)
                    for r in c["ring_red"])
                h_out, reds_k = stage_fwd(stk, h_in, bb_in, dcs,
                                          key_, m, idx)
                red_out = tuple(
                    lax.dynamic_update_slice_in_dim(
                        buf, kk.astype(buf.dtype), idx * k, 0)
                    for buf, kk in zip(red_in, reds_k))
                slot = m % S
                c = dict(c)
                c["stash_h"] = lax.dynamic_update_index_in_dim(
                    c["stash_h"], h_in.astype(c["stash_h"].dtype),
                    slot, 0)
                c["stash_bb"] = {
                    n: lax.dynamic_update_index_in_dim(
                        c["stash_bb"][n], bb_in[n], slot, 0)
                    for n in bb_names}
                c["stash_red"] = tuple(
                    lax.dynamic_update_index_in_dim(sr, ro, slot, 0)
                    for sr, ro in zip(c["stash_red"], red_out))
                last = idx == pp - 1
                c["acc_red"] = tuple(
                    a + jnp.where(last, ro, 0)
                    for a, ro in zip(c["acc_red"], red_out))
                send = zero_sends(c)
                send["h"] = h_out.astype(send["h"].dtype)
                send["bb"] = bb_in
                send["red"] = red_out
                return c, send

            def b_branch(c, t):
                m = (t - (2 * pp - 1 - idx)) // 2
                slot = m % S
                h_in = lax.dynamic_index_in_dim(
                    c["stash_h"], slot, keepdims=False)
                bb_in = {n: lax.dynamic_index_in_dim(
                    c["stash_bb"][n], slot, keepdims=False)
                    for n in bb_names}
                red_buf = tuple(
                    lax.dynamic_index_in_dim(sr, slot, keepdims=False)
                    for sr in c["stash_red"])

                def fwd_for_vjp(stk_, h_, bb_, dcs_):
                    return stage_fwd(stk_, h_, bb_, dcs_, key_, m, idx)

                (h_out, reds_k), vjp_fn = jax.vjp(
                    fwd_for_vjp, stk, h_in, bb_in, dcs)

                last = idx == pp - 1

                # only the LAST stage needs the tail's loss + vjp; a
                # traced `last` would make every stage compute (and
                # then mask) the full logits+CE forward/backward, so
                # gate it with a nested lax.cond — safe because
                # tail_apply contains no collectives
                def run_tail(_):
                    mb_feeds = {n: pick(xs_tail[n], m) for n in t_mb}
                    loss_m, tvjp = jax.vjp(
                        lambda tp, hf, rv, dc: tail_apply(
                            tp, hf, rv, dc, ndconst_tail, mb_feeds,
                            key_, m),
                        tail_d, h_out, red_buf, dcs)
                    g_tp, g_hf, g_rv, g_tdc = tvjp(
                        _vary(jnp.asarray(1.0 / n_micro,
                                          loss_m.dtype), axis))
                    return (loss_m.astype(jnp.float32), g_tp, g_hf,
                            g_rv, g_tdc)

                def skip_tail(_):
                    return (
                        _vary(jnp.zeros((), jnp.float32), axis),
                        jax.tree.map(jnp.zeros_like, tail_d),
                        jnp.zeros_like(h_out),
                        tuple(jnp.zeros_like(r) for r in red_buf),
                        jax.tree.map(jnp.zeros_like, dcs))

                loss_m, g_tp, g_hf, g_rv, g_tdc = lax.cond(
                    last, run_tail, skip_tail, None)
                g_hout = jnp.where(last, g_hf,
                                   c["ring_gh"].astype(g_hf.dtype))
                g_redbuf = tuple(
                    jnp.where(last, gr, rg.astype(gr.dtype))
                    for gr, rg in zip(g_rv, c["ring_gred"]))
                g_red_mine = tuple(
                    lax.dynamic_slice_in_dim(gr, idx * k, k, 0)
                    .astype(rk.dtype)
                    for gr, rk in zip(g_redbuf, reds_k))
                g_stk, g_hin, g_bb, g_dc = vjp_fn(
                    (g_hout.astype(h_out.dtype), g_red_mine))

                def only_last(x):
                    return jnp.where(last, x, 0)

                c = dict(c)
                c["acc_gstk"] = [a + g for a, g in
                                 zip(c["acc_gstk"], g_stk)]
                c["acc_gtail"] = jax.tree.map(
                    lambda a, g: a + only_last(g),
                    c["acc_gtail"], g_tp)
                c["acc_gdc"] = jax.tree.map(
                    lambda a, g1, g2: a + g1 + only_last(g2),
                    c["acc_gdc"], g_dc, g_tdc)
                c["acc_loss"] = c["acc_loss"] + jnp.where(
                    last, loss_m.astype(jnp.float32), 0.0)
                first = idx == 0
                g_bb_tot = {
                    n: c["ring_gbb"][n].astype(g_bb[n].dtype)
                    + g_bb[n] for n in bb_names}
                mi = jnp.clip(m, 0, n_micro - 1)
                c["buf_gh0"] = jnp.where(
                    first,
                    lax.dynamic_update_index_in_dim(
                        c["buf_gh0"],
                        g_hin.astype(c["buf_gh0"].dtype), mi, 0),
                    c["buf_gh0"])
                c["buf_gbb"] = {
                    n: jnp.where(
                        first,
                        lax.dynamic_update_index_in_dim(
                            c["buf_gbb"][n],
                            g_bb_tot[n].astype(c["buf_gbb"][n].dtype),
                            mi, 0),
                        c["buf_gbb"][n])
                    for n in bb_names}
                send = zero_sends(c)
                send["gh"] = g_hin.astype(send["gh"].dtype)
                send["gbb"] = {n: g_bb_tot[n].astype(
                    send["gbb"][n].dtype) for n in bb_names}
                send["gred"] = tuple(
                    g.astype(r.dtype) for g, r in
                    zip(g_redbuf, send["gred"]))
                return c, send

            def idle_branch(c, t):
                return dict(c), zero_sends(c)

            def tick(c, t):
                df = t - idx
                is_f = jnp.logical_and(
                    df % 2 == 0,
                    jnp.logical_and(df >= 0, df // 2 < n_micro))
                db = t - (2 * pp - 1 - idx)
                is_b = jnp.logical_and(
                    db % 2 == 0,
                    jnp.logical_and(db >= 0, db // 2 < n_micro))
                c, send = lax.cond(
                    is_f, f_branch,
                    lambda cc, tt: lax.cond(
                        is_b, b_branch, idle_branch, cc, tt),
                    c, t)
                c["ring_h"] = lax.ppermute(send["h"], axis, fwd_perm)
                c["ring_bb"] = {
                    n: lax.ppermute(send["bb"][n], axis, fwd_perm)
                    for n in bb_names}
                c["ring_red"] = tuple(
                    lax.ppermute(r, axis, fwd_perm)
                    for r in send["red"])
                c["ring_gh"] = lax.ppermute(send["gh"], axis,
                                            bwd_perm)
                c["ring_gbb"] = {
                    n: lax.ppermute(send["gbb"][n], axis, bwd_perm)
                    for n in bb_names}
                c["ring_gred"] = tuple(
                    lax.ppermute(r, axis, bwd_perm)
                    for r in send["gred"])
                return c, None

            c, _ = lax.scan(tick, carry0, jnp.arange(T))
            idx_last = idx == pp - 1
            idx_first = idx == 0

            def msum(x, mask):
                return lax.psum(jnp.where(mask, x, 0), axis)

            loss = msum(c["acc_loss"], idx_last) / n_micro
            g_tail = jax.tree.map(lambda x: msum(x, idx_last),
                                  c["acc_gtail"])
            g_dc = jax.tree.map(lambda x: lax.psum(x, axis),
                                c["acc_gdc"])
            g_h0 = msum(c["buf_gh0"], idx_first)
            g_bb = {n: msum(c["buf_gbb"][n], idx_first)
                    for n in bb_names}
            red_obs = tuple(msum(r, idx_last) / n_micro
                            for r in c["acc_red"])
            return (loss, c["acc_gstk"], g_tail, g_dc, g_h0, g_bb,
                    red_obs)

        fn = jax.shard_map(
            ring, mesh=tr.mesh, axis_names=frozenset({axis}),
            in_specs=([P(axis)] * len(stacked),
                      jax.tree.map(lambda _: P(), tail_diff),
                      jax.tree.map(lambda _: P(), dconsts), P()),
            out_specs=(P(), [P(axis)] * len(stacked),
                       jax.tree.map(lambda _: P(), tail_diff),
                       jax.tree.map(lambda _: P(), dconsts),
                       P(), {n: P() for n in bb_names},
                       tuple(P() for _ in loop.reduce_outs)))
        (loss, g_stk, g_tail, g_dc, g_h0, g_bb, red_obs) = fn(
            stacked, tail_diff, dconsts, loop_key)

        # ---- assemble gradients -------------------------------------
        grads: Dict[str, jax.Array] = {}
        for pos in range(len(loop.canon_params)):
            for s in range(n_seg):
                nm = loop.seg_params[s][pos]
                grads[nm] = grads.get(nm, 0) + g_stk[pos][s]
        for n, g in g_tail.items():
            grads[n] = grads.get(n, 0) + g
        if head_vjp is not None:
            cots = []
            for n in out_names:
                v = hv[n]
                if n == loop.bounds[0]:
                    g = g_h0.reshape(v.shape).astype(v.dtype)
                elif n in g_bb:
                    g = g_bb[n].reshape(v.shape).astype(v.dtype)
                elif n in g_dc:
                    g = g_dc[n].astype(v.dtype)
                else:
                    g = jnp.zeros_like(v)
                cots.append(g)
            head_grads, = head_vjp(tuple(cots))
            for n, g in head_grads.items():
                grads[n] = grads.get(n, 0) + g

        # ---- aux values + phase B -----------------------------------
        for fam, arr in zip(loop.reduce_outs, red_obs):
            for si, nm in enumerate(fam):
                env[nm] = arr[si]
        cell = [jax.random.fold_in(key, 5)]
        for op in aux_ops:
            run_op(op, env, rng_cell=cell, rng_salt=op._uid)
        env[loss_name] = jnp.reshape(loss, (1,))

        env_b = dict(state)
        env_b.update(feeds)
        for n in tr.aux_names:
            if n in env:
                env_b[n] = env[n]
        for n in tr.state_out:
            if n in outside_writes and n in env:
                env_b[n] = env[n]
        for fam in loop.reduce_outs:
            for nm in fam:
                env_b[nm] = env[nm]
        env_b[loss_name] = env[loss_name]
        for n, g in grads.items():
            env_b[grad_var_name(n)] = g
        cellb = [jax.random.fold_in(key, 3)]
        for op in tr.phase_b:
            run_op(op, env_b, rng_cell=cellb, rng_salt=op._uid)
        new_state = dict(state)
        for n in tr.state_names:
            if n in env_b:
                new_state[n] = env_b[n]
        fetches = {}
        for n in extra_fetches:
            if n in env_b:
                fetches[n] = env_b[n]
            elif n in env:
                fetches[n] = env[n]
            else:
                from .pipeline_program import PipelineFetchError

                raise PipelineFetchError(
                    f"fetch target {n!r} is not materialized by the "
                    f"1f1b schedule: it is neither the loss, a "
                    f"persistable, a head-section var, a gradient, "
                    f"nor a loop reduce output. Tail activations are "
                    f"computed per microbatch inside the ring — "
                    f"fetch them through schedule='gpipe'.")
        return new_state, jnp.reshape(loss, ()), fetches, rng_next

    return step
