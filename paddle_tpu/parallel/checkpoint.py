"""Sharded (orbax-style) checkpointing for multi-chip state.

Parity: reference io.py:263 _save_distributed_persistables saves each
node's slice of split/distributed vars and io.py:501
load_persist_vars_without_grad re-assembles on load; SURVEY.md §5 calls
for the orbax-style per-shard form on TPU.

Design: each process writes ONLY the addressable shards of each
jax.Array (one .npy per shard + a JSON manifest of global shape/dtype
and per-shard index ranges). Load re-assembles against a TARGET
sharding that may differ from the one saved (mesh change on restore):
per target device, the required global slice is cut from the saved
shards, and jax.make_array_from_single_device_arrays builds the new
array without ever materializing more than each device's piece --
plus a simple full-host path for unsharded restores.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np

import jax

__all__ = ["save_sharded", "load_sharded", "load_manifest"]

_MANIFEST = "manifest.json"


def _slice_spec(index, shape):
    """(slice,...) -> [[start, stop], ...] JSON-able, Nones resolved."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_sharded(dirname: str, arrays: Dict[str, "jax.Array"],
                 process_index: Optional[int] = None) -> None:
    """Write this process's shards of every array + the manifest.

    Replicated shards are written once (replica_id == 0 only), so a
    fully-replicated array costs one file, and each process of a
    multi-host job writes a disjoint set.
    """
    pidx = (jax.process_index() if process_index is None
            else process_index)
    shard_dir = os.path.join(dirname, "shards")
    os.makedirs(shard_dir, exist_ok=True)
    manifest = {}
    for name, arr in arrays.items():
        arr = jax.numpy.asarray(arr) if not isinstance(arr, jax.Array) \
            else arr
        if not arr.addressable_shards:
            # multi-host: entirely on other processes' devices; their
            # manifests carry it (load merges all manifests)
            continue
        entries = []
        for i, shard in enumerate(arr.addressable_shards):
            if shard.replica_id != 0:
                continue  # another device holds the same bytes
            spec = _slice_spec(shard.index, arr.shape)
            fname = f"{name}.p{pidx}.s{i}.npy"
            np.save(os.path.join(shard_dir, fname),
                    np.asarray(shard.data), allow_pickle=False)
            entries.append({"file": fname, "index": spec})
        manifest[name] = {
            "shape": list(arr.shape),
            "dtype": str(np.dtype(arr.dtype)),
            "shards": entries,
        }
    # per-process manifest; process 0's name is the canonical one
    mpath = os.path.join(
        dirname, _MANIFEST if pidx == 0 else f"manifest.{pidx}.json")
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)


def load_manifest(dirname: str) -> Dict:
    """Merge all processes' manifests into one shard map."""
    merged = {}
    for fname in sorted(os.listdir(dirname)):
        if not (fname == _MANIFEST or
                (fname.startswith("manifest.") and
                 fname.endswith(".json"))):
            continue
        with open(os.path.join(dirname, fname)) as f:
            part = json.load(f)
        for name, meta in part.items():
            if name not in merged:
                merged[name] = {"shape": meta["shape"],
                                "dtype": meta["dtype"], "shards": []}
            merged[name]["shards"].extend(meta["shards"])
    return merged


def _read_global(dirname: str, meta) -> np.ndarray:
    """Assemble one var's full array from its shard files."""
    out = np.zeros(tuple(meta["shape"]), dtype=np.dtype(meta["dtype"]))
    for e in meta["shards"]:
        idx = tuple(slice(a, b) for a, b in e["index"])
        out[idx] = np.load(os.path.join(dirname, "shards", e["file"]),
                           allow_pickle=False)
    return out


def _resolve_index(idx, shape):
    """device index (slice tuple, possibly partial) -> concrete
    [[start, stop], ...] over every dim."""
    idx = tuple(idx) + (slice(None),) * (len(shape) - len(idx))
    return [(0 if s.start is None else int(s.start),
             d if s.stop is None else int(s.stop))
            for s, d in zip(idx, shape)]


def _read_slice(dirname, meta, bounds):
    """Assemble ONE target slice from only the overlapping shard files
    -- peak host memory is the slice plus one shard, never the global
    array (the pod-scale contract in the module docstring)."""
    out = np.zeros([b - a for a, b in bounds],
                   dtype=np.dtype(meta["dtype"]))
    for e in meta["shards"]:
        inter = [(max(a, sa), min(b, sb))
                 for (a, b), (sa, sb) in zip(bounds, e["index"])]
        if any(a >= b for a, b in inter):
            continue  # no overlap with this shard
        shard = np.load(os.path.join(dirname, "shards", e["file"]),
                        allow_pickle=False)
        src = tuple(slice(a - sa, b - sa)
                    for (a, b), (sa, _) in zip(inter, e["index"]))
        dst = tuple(slice(a - ta, b - ta)
                    for (a, b), (ta, _) in zip(inter, bounds))
        out[dst] = shard[src]
    return out


def load_sharded(dirname: str, shardings: Optional[Dict] = None,
                 names=None, manifest: Optional[Dict] = None
                 ) -> Dict[str, "jax.Array"]:
    """Restore arrays; `shardings` maps name -> target Sharding (or a
    single Sharding for all). A target that differs from the saved
    layout is fine -- each target device gets exactly its slice, read
    from only the overlapping shard files."""
    if manifest is None:
        manifest = load_manifest(dirname)
    if names is not None:
        manifest = {n: manifest[n] for n in names}
    out = {}
    for name, meta in manifest.items():
        target = None
        if shardings is not None:
            target = (shardings.get(name)
                      if isinstance(shardings, dict) else shardings)
        if target is None:
            out[name] = _read_global(dirname, meta)
            continue
        shape = tuple(meta["shape"])
        indices = target.addressable_devices_indices_map(shape)
        # replicated targets repeat the same slice: assemble each
        # DISTINCT slice once, device_put per device
        cache = {}
        dev_arrays = []
        for dev, idx in indices.items():
            bounds = tuple(map(tuple, _resolve_index(idx, shape)))
            if bounds not in cache:
                cache[bounds] = _read_slice(dirname, meta,
                                            list(bounds))
            dev_arrays.append(jax.device_put(cache[bounds], dev))
        out[name] = jax.make_array_from_single_device_arrays(
            shape, target, dev_arrays)
    return out
