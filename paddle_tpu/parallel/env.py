"""Multi-host bootstrap (replaces reference gen_nccl_id_op.cc:31 raw-RPC
ncclUniqueId broadcast + PADDLE_* env topology of test_dist_base.py).

jax.distributed's coordination service fills the gen_nccl_id role: rank 0
hosts the coordinator, others connect, and XLA's runtime builds the
ICI/DCN communicator -- no framework-level RPC plumbing. The PADDLE_*
env-var contract is honored for drop-in launch-script compatibility.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import jax


@dataclass
class DistributedEnv:
    trainer_id: int = 0
    num_trainers: int = 1
    coordinator: Optional[str] = None
    role: str = "TRAINER"

    @property
    def is_chief(self):
        return self.trainer_id == 0


def _from_env() -> DistributedEnv:
    """Reads both the reference's PADDLE_* contract and jax-style vars."""
    env = os.environ
    trainer_id = int(env.get("PADDLE_TRAINER_ID",
                             env.get("JAX_PROCESS_ID", 0)))
    num = int(env.get("PADDLE_TRAINERS_NUM",
                      env.get("JAX_NUM_PROCESSES", 1)))
    eps = env.get("PADDLE_TRAINER_ENDPOINTS", "")
    coordinator = env.get("JAX_COORDINATOR_ADDRESS")
    if coordinator is None and eps:
        coordinator = eps.split(",")[0]
    role = env.get("PADDLE_TRAINING_ROLE", "TRAINER")
    return DistributedEnv(trainer_id, num, coordinator, role)


_initialized = [False]


def init_distributed_env(env: Optional[DistributedEnv] = None
                         ) -> DistributedEnv:
    env = env or _from_env()
    if env.num_trainers > 1 and not _initialized[0]:
        jax.distributed.initialize(
            coordinator_address=env.coordinator,
            num_processes=env.num_trainers,
            process_id=env.trainer_id)
        _initialized[0] = True
    return env
