"""Ring attention: exact sequence/context-parallel attention over an
'sp' mesh axis.

This is a capability the reference does not have (SURVEY.md §5: no
ring/context parallelism -- its long-sequence story is LoDTensor
batching); it is the TPU-native mechanism that lets attention scale past
one chip's HBM: Q stays put, K/V blocks rotate around the ICI ring via
`ppermute` while each device accumulates flash-style online softmax
(running max / denominator) in fp32, so the full [T, T] logits matrix
never materializes anywhere.

Two context-parallel schemes are provided:
  * ring_attention      -- K/V rotation (ring; comm ~ T*D per step,
                           overlappable with compute on ICI)
  * ulysses_attention   -- all_to_all head-scatter (DeepSpeed-Ulysses
                           style): re-shard seq->heads, run dense local
                           attention, re-shard back. Cheaper at modest
                           sp when heads % sp == 0.

Both are pure jax (scan + ppermute / all_to_all), differentiable with
standard AD (ppermute transposes to the inverse permutation), and are
meant to be called inside `shard_map` -- `ring_self_attention` wraps
that for [B, H, T, D] operands sharded on T.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_scores(q, k, scale):
    # q: [B,H,Tq,D], k: [B,H,Tk,D] -> [B,H,Tq,Tk] fp32
    return jnp.einsum("bhqd,bhkd->bhqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def ring_attention_local(q, k, v, axis_name: str, *, scale: float,
                         causal: bool = True):
    """Per-shard body: call inside shard_map. q/k/v: [B,H,Tl,D] local
    sequence blocks; returns local attention output [B,H,Tl,D].

    Device i's Q block attends to every K/V block as they rotate by
    `ppermute`; online-softmax carry (m, l, o) merges partial results
    exactly (same math as the Pallas flash kernel in
    ops/pallas/attention.py, but across chips).
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, tl, d = q.shape
    q32 = q.astype(jnp.float32)

    q_pos = my * tl + jnp.arange(tl)                      # global q rows
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        k_blk, v_blk, m, l, o = carry
        # after s rotations device `my` holds the block that started on
        # device (my - s) mod n
        src = (my - s) % n
        scores = _block_scores(q32, k_blk.astype(jnp.float32), scale)
        if causal:
            kv_pos = src * tl + jnp.arange(tl)
            mask = q_pos[:, None] >= kv_pos[None, :]
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        safe_m = jnp.where(m_new <= NEG_INF, 0.0, m_new)
        p = jnp.exp(scores - safe_m[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.where(m <= NEG_INF, 0.0, jnp.exp(m - safe_m))
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m_new, l, o), None

    # derive zero-inits from the operands so they inherit the operands'
    # varying mesh axes (sp, and dp/tp when composed) -- shard_map's
    # varying-axes check requires scan carry in/out types to match
    qk0 = q32[..., 0] * 0.0 + (k[..., 0, 0] * 0.0)[..., None]
    m0 = qk0 + NEG_INF
    l0 = qk0
    o0 = (q32 * 0.0) + (v[..., 0, 0] * 0.0)[..., None, None]
    (_, _, _, l, o), _ = lax.scan(step, (k, v, m0, l0, o0),
                                  jnp.arange(n))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name: str, *, scale: float,
                            causal: bool = True):
    """All-to-all context parallelism: re-shard [B, H, T/n, D] ->
    [B, H/n, T, D], dense local attention over the FULL sequence, then
    re-shard back. Requires H % axis_size == 0."""
    n = lax.psum(1, axis_name)
    b, h, tl, d = q.shape
    assert h % n == 0, f"ulysses needs heads({h}) % sp({n}) == 0"

    def seq2head(x):   # [B,H,Tl,D] -> [B,H/n,T,D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def head2seq(x):   # [B,H/n,T,D] -> [B,H,Tl,D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qf, kf, vf = seq2head(q), seq2head(k), seq2head(v)
    t = qf.shape[2]
    scores = _block_scores(qf.astype(jnp.float32),
                           kf.astype(jnp.float32), scale)
    if causal:
        pos = jnp.arange(t)
        scores = jnp.where(pos[:, None] >= pos[None, :], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    of = jnp.einsum("bhqk,bhkd->bhqd", p, vf.astype(jnp.float32))
    return head2seq(of.astype(q.dtype))


def _sp_sharded_call(local_fn, mesh: Mesh, axis: str, q, k, v):
    # [B, H, T, D]: T over the sp axis; batch/heads additionally ride
    # any dp/tp axes in the same mesh so context parallelism composes
    # with data/tensor parallelism in one shard_map
    def ax(name):
        return name if mesh.shape.get(name, 1) > 1 and name != axis \
            else None

    spec = P(ax("dp"), ax("tp"), axis, None)
    fn = jax.shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn(q, k, v)


# --- context-parallel activation scope -----------------------------------
# The Program executor's `attention` op (ops/nn_ops.py) consults this so
# sequence parallelism composes with the graph path: inside the scope,
# eligible self-attention ops lower to shard_map ring attention over the
# given mesh axis instead of single-shard flash attention.
_ACTIVE_CP = None


class context_parallel:
    """`with context_parallel(mesh, axis='sp', impl='ring'):` -- route
    framework attention ops through sequence-parallel attention."""

    def __init__(self, mesh: Mesh, axis: str = "sp", impl: str = "ring"):
        self.cfg = (mesh, axis, impl)

    def __enter__(self):
        global _ACTIVE_CP
        self._prev = _ACTIVE_CP
        _ACTIVE_CP = self.cfg
        return self

    def __exit__(self, *a):
        global _ACTIVE_CP
        _ACTIVE_CP = self._prev


def active_context_parallel():
    return _ACTIVE_CP


def cp_applicable(q, k, v, dropout_rate) -> bool:
    """Self-attention with equal q/kv length, no attention dropout, and
    a sequence length divisible by the sp axis size."""
    if _ACTIVE_CP is None or dropout_rate:
        return False
    mesh, axis, _ = _ACTIVE_CP
    n = mesh.shape[axis]
    dp = mesh.shape.get("dp", 1)
    tp = mesh.shape.get("tp", 1)
    return (q.shape == k.shape == v.shape and n > 1
            and q.shape[2] % n == 0 and q.shape[0] % dp == 0
            and q.shape[1] % tp == 0)


def cp_attention(q, k, v, scale, causal):
    mesh, axis, impl = _ACTIVE_CP
    body = {"ring": ring_attention_local,
            "ulysses": ulysses_attention_local}[impl]
    local = functools.partial(body, axis_name=axis, scale=scale,
                              causal=causal)
    return _sp_sharded_call(local, mesh, axis, q, k, v)


def ring_self_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                        scale: Optional[float] = None,
                        causal: bool = True, impl: str = "ring"):
    """Context-parallel attention over `mesh` axis `axis`.

    q, k, v: [B, H, T, D] global operands (host or device arrays); the
    sequence dim is sharded over the axis and attention runs exactly as
    if on one device. `impl` in {"ring", "ulysses"}.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    body = {"ring": ring_attention_local,
            "ulysses": ulysses_attention_local}[impl]
    local = functools.partial(body, axis_name=axis, scale=scale,
                              causal=causal)
    spec = NamedSharding(mesh, P(None, None, axis, None))
    q, k, v = (jax.device_put(x, spec) for x in (q, k, v))
    return _sp_sharded_call(local, mesh, axis, q, k, v)
