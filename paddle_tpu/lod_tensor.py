"""LoD tensor helpers (parity: reference python/paddle/fluid/
lod_tensor.py: create_lod_tensor, create_random_int_lodtensor).

TPU encoding note: the framework's native variable-length encoding is
padded-dense [B, maxlen, ...] + an int32 per-sample length companion
(layers/sequence.py @SEQ_LEN contract); these helpers build that pair
from the reference's recursive_seq_lens representation, and convert
back — the round-trip the reference's LoDTensor.set_lod/lod provides.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

__all__ = ["create_lod_tensor", "create_random_int_lodtensor",
           "to_padded", "from_padded", "lengths_to_offsets",
           "offsets_to_lengths"]


def lengths_to_offsets(lengths: Sequence[int]) -> List[int]:
    out = [0]
    for l in lengths:
        out.append(out[-1] + int(l))
    return out


def offsets_to_lengths(offsets: Sequence[int]) -> List[int]:
    return [int(offsets[i + 1] - offsets[i])
            for i in range(len(offsets) - 1)]


class LoDTensor:
    """Value + recursive sequence lengths (reference lod_tensor.h:110
    semantics at the Python surface)."""

    def __init__(self, data: np.ndarray,
                 recursive_seq_lens: List[List[int]]):
        self._data = np.asarray(data)
        self._lens = [list(map(int, l)) for l in recursive_seq_lens]

    def lod(self):
        return [lengths_to_offsets(l) for l in self._lens]

    def recursive_sequence_lengths(self):
        return self._lens

    def set_lod(self, lod):
        self._lens = [offsets_to_lengths(l) for l in lod]

    def __array__(self, dtype=None):
        return self._data.astype(dtype) if dtype else self._data

    def shape(self):
        return list(self._data.shape)

    def has_valid_recursive_sequence_lengths(self) -> bool:
        total = self._data.shape[0]
        lens = self._lens
        for level in reversed(range(len(lens))):
            if sum(lens[level]) != total:
                return False
            total = len(lens[level])
        return True


def create_lod_tensor(data, recursive_seq_lens, place=None) -> LoDTensor:
    """reference lod_tensor.py create_lod_tensor: data may be a numpy
    array (rows = sum of bottom-level lens), a list of lists, or
    another LoDTensor."""
    if isinstance(data, LoDTensor):
        t = LoDTensor(np.asarray(data), recursive_seq_lens)
        assert t.has_valid_recursive_sequence_lengths(), \
            "invalid recursive_seq_lens for LoDTensor with %d rows" % \
            np.asarray(data).shape[0]
        return t
    if isinstance(data, list):
        flat = [np.asarray(x).reshape(-1, 1) for x in data]
        arr = np.concatenate(flat, axis=0)
        assert [len(x) for x in flat] == list(
            recursive_seq_lens[-1]), \
            "list data lengths must match recursive_seq_lens[-1]"
        return LoDTensor(arr, recursive_seq_lens)
    arr = np.asarray(data)
    t = LoDTensor(arr, recursive_seq_lens)
    assert t.has_valid_recursive_sequence_lengths(), \
        "invalid recursive_seq_lens for data with %d rows" % \
        arr.shape[0]
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high) -> LoDTensor:
    rows = sum(recursive_seq_lens[-1])
    shape = [rows] + list(base_shape)
    return LoDTensor(
        np.random.randint(low, high + 1, size=shape).astype(np.int64),
        recursive_seq_lens)


def to_padded(t: LoDTensor) -> Tuple[np.ndarray, np.ndarray]:
    """LoDTensor -> (padded [B, maxlen, ...], lengths int32 [B]): the
    framework's native encoding (feed the pair as `name` +
    `name@SEQ_LEN`)."""
    lens = t.recursive_sequence_lengths()[-1]
    data = np.asarray(t)
    maxlen = max(lens) if lens else 0
    out = np.zeros((len(lens), maxlen) + data.shape[1:], data.dtype)
    off = 0
    for i, l in enumerate(lens):
        out[i, :l] = data[off:off + l]
        off += l
    return out, np.asarray(lens, np.int32)


def from_padded(padded: np.ndarray, lengths) -> LoDTensor:
    rows = []
    for i, l in enumerate(np.asarray(lengths)):
        rows.append(padded[i, :int(l)])
    return LoDTensor(np.concatenate(rows, axis=0),
                     [[int(l) for l in np.asarray(lengths)]])
