"""DataFeeder (reference python/paddle/fluid/data_feeder.py:140).

Converts python/numpy minibatch rows into the feed dict the Executor
expects. Fluid's LoD conversion (list-of-variable-length-rows ->
LoDTensor) becomes padded-dense + @SEQ_LEN companion arrays here
(see layers/sequence.py for the representation contract).
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .core.program import Variable, default_main_program
from .core.types import to_np_dtype
from .layers.sequence import SEQ_LEN_SUFFIX


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None):
        self.program = program or default_main_program()
        # a place makes feed() return DEVICE arrays: jax.device_put is
        # async, so converting a batch while the previous step runs
        # overlaps its H2D transfer with compute (the reference's
        # buffered_reader H2D staging, reader.py double buffer)
        self.place = place
        self.feed_vars = []
        for v in feed_list:
            if isinstance(v, str):
                v = self.program.global_block.var(v)
            self.feed_vars.append(v)

    def feed(self, iterable) -> dict:
        """iterable: list of rows, each row a tuple aligned with
        feed_list entries."""
        columns = list(zip(*iterable))
        result = {}
        for var, col in zip(self.feed_vars, columns):
            if var.lod_level and var.lod_level > 0:
                data, lengths = _pad_sequences(col, var)
                result[var.name] = data
                result[var.name + SEQ_LEN_SUFFIX] = lengths
            else:
                arr = np.asarray(col)
                dtype = to_np_dtype(var.dtype) if var.dtype else None
                if dtype is not None and arr.dtype != dtype:
                    arr = arr.astype(dtype)
                # fluid reshapes rows to the var's trailing dims
                if var.shape and len(var.shape) > 1:
                    trail = [d for d in var.shape[1:]]
                    if all(d > 0 for d in trail):
                        arr = arr.reshape([arr.shape[0]] + trail)
                result[var.name] = arr
        if self.place is not None:
            import jax

            try:
                device = self.place.device()
            except Exception:
                device = None
            if device is not None:
                result = {k: jax.device_put(v, device)
                          for k, v in result.items()}
        return result


def _pad_sequences(col, var: Variable):
    """list of per-example variable-length sequences -> padded + lengths,
    rounded up to a small bucket to bound XLA recompiles."""
    seqs = [np.asarray(s) for s in col]
    lengths = np.asarray([len(s) for s in seqs], dtype=np.int32)
    max_len = int(max(1, lengths.max()))
    # bucket to multiples of 16 to cap distinct compiled shapes
    bucket = 16
    max_len = ((max_len + bucket - 1) // bucket) * bucket
    trailing = seqs[0].shape[1:] if seqs[0].ndim > 1 else ()
    dtype = to_np_dtype(var.dtype) if var.dtype else seqs[0].dtype
    out = np.zeros((len(seqs), max_len) + tuple(trailing), dtype=dtype)
    for i, s in enumerate(seqs):
        out[i, :len(s)] = s
    return out, lengths
