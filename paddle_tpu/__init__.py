"""paddle_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle Fluid (reference mounted at /root/reference).

Architecture (vs the reference's interpret-the-graph design):
  Python builds a Program (program-as-data, like fluid) ->
  Executor lowers whole blocks through JAX to ONE XLA computation ->
  XLA schedules fusion/memory/collectives on TPU (MXU for matmuls,
  ICI collectives via sharding annotations instead of NCCL op handles).

Top-level API mirrors `paddle.fluid`: layers, Program, Executor,
optimizer, backward, io, initializer, ParamAttr, CompiledProgram...
"""
from . import ops as _ops  # registers all kernels
from .core.program import (Program, Block, Variable, Operator,
                           default_main_program, default_startup_program,
                           program_guard, switch_main_program,
                           switch_startup_program)
from .core.executor import (Executor, TPUPlace, CPUPlace, CUDAPlace,
                            CUDAPinnedPlace,
                            seed)
from .core.scope import Scope, global_scope, _reset_global_scope
from .core import registry as _registry
from .core.registry import registered_ops
from .backward import append_backward, gradients
from .param_attr import ParamAttr, WeightNormParamAttr
from . import layers
from . import initializer
from . import optimizer
from . import regularizer
from . import clip
from . import unique_name
from . import nets
from . import metrics
from . import profiler
from . import observability
from .io import (save_vars, save_params, save_persistables, load_vars,
                 load_params, load_persistables, save_inference_model,
                 load_inference_model, save_sharded_persistables,
                 load_sharded_persistables)
from .core.compiler import CompiledProgram, BuildStrategy, \
    ExecutionStrategy, ParallelExecutor
from .data_feeder import DataFeeder
from .reader import PyReader
from . import dygraph
from . import readers
from .readers import batch
from . import dataset
from . import ir
from . import inference
from . import transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig, \
    memory_optimize, release_memory, InferenceTranspiler
from . import distributed
from . import distribute_lookup_table
from . import amp
from . import flags
from .flags import set_flags, get_flags
from . import enforce
from .enforce import EnforceNotMet
from . import train_checkpoint
from .train_checkpoint import TrainCheckpoint
from . import contrib
from . import lod_tensor
from .lod_tensor import create_lod_tensor, create_random_int_lodtensor
from . import average
from .average import WeightedAverage  # noqa: F401
from . import recordio_writer  # noqa: F401
from .lod_tensor import LoDTensor  # noqa: F401
# reference fluid exposes Tensor as an alias of LoDTensor
# (python/paddle/fluid/__init__.py Tensor = LoDTensor)
Tensor = LoDTensor
LoDTensorArray = list
from .layers import learning_rate_scheduler as learning_rate_decay  # noqa: F401,E402
from . import debugger
from . import net_drawer
from . import evaluator
from . import install_check
from .async_executor import AsyncExecutor
from .data_feed import DataFeedDesc

# fluid-compat: many scripts do `import paddle.fluid as fluid`; we expose
# the same names so `import paddle_tpu as fluid` works.
name_scope = program_guard


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        from .core import scope as scope_mod

        old = scope_mod._global_scope
        scope_mod._global_scope = scope
        try:
            yield
        finally:
            scope_mod._global_scope = old

    return _guard()


def cuda_places(device_ids=None):
    import jax

    n = len(jax.devices())
    ids = device_ids if device_ids is not None else range(n)
    return [TPUPlace(i) for i in ids]


def cpu_places(device_count=None):
    return [CPUPlace()]


def cuda_pinned_places(device_count=None):
    """reference framework.py:153 cuda_pinned_places: page-locked
    staging buffers. XLA manages host staging itself; returns
    CUDAPinnedPlace objects (CPU-backed) for isinstance parity."""
    return [CUDAPinnedPlace() for _ in range(device_count or 1)]


def device_count():
    import jax

    return len(jax.devices())


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return True


__version__ = "0.1.0"
