"""Enforce: check helpers raising EnforceNotMet with call context.

Parity: reference paddle/fluid/platform/enforce.h (PADDLE_ENFORCE*,
:245 -- CUDA-error decoding and C++ stack traces). The TPU build's
device errors surface through jax/XLA exceptions already, so the
Python layer keeps the reference's *check* surface: a structured
error type plus the comparison helpers op builders and user code can
call at program-construction time (where the reference fires most of
its ENFORCEs, via InferShape)."""
from __future__ import annotations

import sys
from types import SimpleNamespace


class EnforceNotMet(RuntimeError):
    """reference platform/enforce.h EnforceNotMet: carries the failing
    expression/message and the python call site."""

    def __init__(self, message, frame=None):
        if frame is not None:
            message = (f"{message}\n  at {frame.filename}:"
                       f"{frame.lineno} in {frame.function}")
        super().__init__(message)


# spelling used by the analysis gate docs (FLAGS_static_check=strict
# "raises EnforceError"); same type, both names resolve
EnforceError = EnforceNotMet


def _caller():
    # sys._getframe: one frame fetch, no per-frame source-context
    # reads like inspect.stack() would do for the WHOLE stack
    try:
        f = sys._getframe(2)  # [0]=_caller [1]=enforce_* [2]=call site
    except ValueError:
        return None
    return SimpleNamespace(filename=f.f_code.co_filename,
                           lineno=f.f_lineno,
                           function=f.f_code.co_name)


def enforce(cond, msg="enforce failed"):
    if not cond:
        raise EnforceNotMet(msg, _caller())


def enforce_eq(a, b, msg=None):
    if a != b:
        raise EnforceNotMet(msg or f"enforce_eq failed: {a!r} != {b!r}",
                            _caller())


def enforce_ne(a, b, msg=None):
    if a == b:
        raise EnforceNotMet(msg or f"enforce_ne failed: both {a!r}",
                            _caller())


def enforce_gt(a, b, msg=None):
    if not a > b:
        raise EnforceNotMet(msg or f"enforce_gt failed: {a!r} <= {b!r}",
                            _caller())


def enforce_ge(a, b, msg=None):
    if not a >= b:
        raise EnforceNotMet(msg or f"enforce_ge failed: {a!r} < {b!r}",
                            _caller())


def enforce_lt(a, b, msg=None):
    if not a < b:
        raise EnforceNotMet(msg or f"enforce_lt failed: {a!r} >= {b!r}",
                            _caller())


def enforce_le(a, b, msg=None):
    if not a <= b:
        raise EnforceNotMet(msg or f"enforce_le failed: {a!r} > {b!r}",
                            _caller())


def enforce_not_none(v, msg=None):
    if v is None:
        raise EnforceNotMet(msg or "enforce_not_none failed", _caller())
    return v
