"""Crash-resumable training checkpoints with retention.

Beyond-reference capability (SURVEY.md §5 "failure detection": the
reference's story is manual re-launch + load_persistables; it calls
this a gap for the TPU build to exceed). TrainCheckpoint wraps the
existing io.save/load machinery with:

  - numbered step directories + an atomically-renamed LATEST marker,
    so a crash mid-save can never corrupt the resume point
  - max_to_keep retention
  - resume() that restores persistables AND returns the step to
    continue from (0 when no checkpoint exists)

Usage::

    ck = TrainCheckpoint(dirname, exe, main_program, max_to_keep=3)
    start = ck.resume()
    for step in range(start, max_steps):
        exe.run(...)
        if step % 100 == 0:
            ck.save(step)
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile

from . import io as _io

_LATEST = "LATEST"


class TrainCheckpoint:
    def __init__(self, dirname, executor, main_program=None,
                 max_to_keep=3, sharded=False):
        self._dir = str(dirname)
        self._exe = executor
        self._prog = main_program
        self._keep = int(max_to_keep)
        self._sharded = bool(sharded)
        if self._process_index() == 0:
            os.makedirs(self._dir, exist_ok=True)
            self._sweep_orphans()
        self._barrier()

    @staticmethod
    def _process_index():
        try:
            import jax

            return jax.process_index()
        except Exception:
            return 0

    @staticmethod
    def _process_count():
        try:
            import jax

            return jax.process_count()
        except Exception:
            return 1

    def _barrier(self):
        if self._process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("train_checkpoint")

    def _sweep_orphans(self):
        # kill -9 mid-save leaves full-size staging dirs behind; they
        # are garbage by construction (never published)
        for name in os.listdir(self._dir):
            if name.startswith(".ck_"):
                shutil.rmtree(os.path.join(self._dir, name),
                              ignore_errors=True)

    # -- paths ---------------------------------------------------------
    def _step_dir(self, step):
        return os.path.join(self._dir, f"step_{int(step)}")

    def _list_steps(self):
        steps = []
        for name in os.listdir(self._dir):
            if name.startswith("step_"):
                try:
                    steps.append(int(name[5:]))
                except ValueError:
                    continue
        return sorted(steps)

    def latest_step(self):
        """The newest COMPLETED save: the marker's step when its dir
        survives, else the newest on-disk step dir (marker corruption
        or a lost dir must not silently restart training at 0)."""
        marker = os.path.join(self._dir, _LATEST)
        step = None
        if os.path.exists(marker):
            try:
                with open(marker) as f:
                    step = int(json.load(f)["step"])
            except (ValueError, KeyError, json.JSONDecodeError):
                step = None  # truncated marker (e.g. power loss)
        if step is not None and os.path.isdir(self._step_dir(step)):
            return step
        steps = self._list_steps()
        return steps[-1] if steps else None

    # -- save / resume -------------------------------------------------
    def save(self, step):
        """Write persistables for `step`; publish atomically; prune.

        Multi-process sharded saves: every process writes its shards
        into the SAME deterministic staging dir (save_sharded writes
        disjoint files per process); rank 0 publishes after a
        barrier. Re-saving an existing step renames the old dir aside
        before the publish rename -- there is no window where the
        marker points at a deleted directory."""
        final = self._step_dir(step)
        if self._sharded and self._process_count() > 1:
            tmp = os.path.join(self._dir, f".ck_incoming_{int(step)}")
            if self._process_index() == 0:
                os.makedirs(tmp, exist_ok=True)
            self._barrier()
        else:
            tmp = tempfile.mkdtemp(prefix=".ck_tmp_", dir=self._dir)
        try:
            if self._sharded:
                _io.save_sharded_persistables(self._exe, tmp,
                                              self._prog)
            else:
                _io.save_persistables(self._exe, tmp, self._prog)
            self._barrier()  # all shards on disk before publish
            if self._process_index() == 0:
                old_aside = None
                if os.path.isdir(final):
                    old_aside = os.path.join(
                        self._dir, f".ck_old_{int(step)}")
                    shutil.rmtree(old_aside, ignore_errors=True)
                    os.rename(final, old_aside)
                os.rename(tmp, final)
                if old_aside is not None:
                    shutil.rmtree(old_aside, ignore_errors=True)
        except BaseException:
            if self._process_index() == 0:
                shutil.rmtree(tmp, ignore_errors=True)
            raise
        if self._process_index() == 0:
            # marker rename is atomic: readers see old-or-new
            marker_tmp = os.path.join(self._dir, _LATEST + ".tmp")
            with open(marker_tmp, "w") as f:
                json.dump({"step": int(step)}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(marker_tmp, os.path.join(self._dir, _LATEST))
            self._prune(keep_also=step)
        self._barrier()
        return final

    def _prune(self, keep_also):
        steps = [s for s in self._list_steps() if s != keep_also]
        for s in steps[:max(0, len(steps) - (self._keep - 1))]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def resume(self):
        """Restore the newest completed checkpoint; returns the next
        step to run (saved step + 1), or 0 with untouched state when
        no checkpoint exists."""
        step = self.latest_step()
        if step is None:
            return 0
        path = self._step_dir(step)
        if self._sharded:
            _io.load_sharded_persistables(self._exe, path, self._prog)
        else:
            _io.load_persistables(self._exe, path, self._prog)
        return step + 1
