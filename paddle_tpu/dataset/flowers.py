"""Oxford-102 flowers readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/flowers.py — yields
(float32[3*224*224] image, int label in [0,102)). Used by the
resnet/se_resnext benchmark models.
"""
from __future__ import annotations

import numpy as np

N_CLASSES = 102
TRAIN_SIZE = 1024
TEST_SIZE = 128


def _make_reader(n, seed, shape=(3, 224, 224)):
    dim = int(np.prod(shape))

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            lab = int(rng.randint(0, N_CLASSES))
            base = (lab / N_CLASSES) - 0.5
            img = (base + rng.normal(0, 0.3, size=dim)).astype(np.float32)
            yield img, lab

    return reader


def _with_mapper(reader, mapper, buffered_size, use_xmap):
    if mapper is None:
        return reader
    from ..readers import map_readers, xmap_readers

    if use_xmap:
        return xmap_readers(mapper, reader, 4, buffered_size, order=True)
    return map_readers(mapper, reader)


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return _with_mapper(_make_reader(TRAIN_SIZE, seed=108), mapper,
                        buffered_size, use_xmap)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return _with_mapper(_make_reader(TEST_SIZE, seed=109), mapper,
                        buffered_size, use_xmap)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _with_mapper(_make_reader(TEST_SIZE, seed=110), mapper,
                        buffered_size, use_xmap)
