"""IMDB sentiment readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/imdb.py — word_dict() maps token
-> id (with '<unk>'); train(word_dict)/test(word_dict) yield
(word_id_list, label in {0,1}). Synthetic corpus: two vocab regions are
class-correlated so sentiment models converge.
"""
from __future__ import annotations

import numpy as np

VOCAB_SIZE = 5148  # matches reference imdb.word_dict() cardinality order
TRAIN_SIZE = 2048
TEST_SIZE = 512


def word_dict():
    d = {"w%d" % i: i for i in range(VOCAB_SIZE - 1)}
    d["<unk>"] = VOCAB_SIZE - 1
    return d


def _make_reader(word_idx, n, seed):
    vocab = len(word_idx)

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 100))
            # class-correlated halves of the vocabulary + common words
            lo = 0 if label == 0 else vocab // 2
            ids = np.where(
                rng.uniform(size=length) < 0.7,
                rng.randint(lo, lo + vocab // 2, size=length),
                rng.randint(0, vocab, size=length))
            yield [int(i) for i in ids], label

    return reader


def train(word_idx):
    return _make_reader(word_idx, TRAIN_SIZE, seed=98)


def test(word_idx):
    return _make_reader(word_idx, TEST_SIZE, seed=99)
