"""Image preprocessing utilities (numpy; no cv2 dependency).

Parity: reference python/paddle/dataset/image.py — resize, center/random
crop, flip, normalization, CHW conversion, and the simple_transform /
load_and_transform composition used by flowers/imagenet pipelines.
"""
from __future__ import annotations

import numpy as np


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Resize so the short edge == size (nearest-neighbor, HWC input)."""
    h, w = im.shape[:2]
    if h < w:
        new_h, new_w = size, int(round(w * size / h))
    else:
        new_h, new_w = int(round(h * size / w)), size
    ri = np.clip((np.arange(new_h) * h / new_h), 0, h - 1).astype(int)
    ci = np.clip((np.arange(new_w) * w / new_w), 0, w - 1).astype(int)
    return im[ri][:, ci]


def to_chw(im: np.ndarray, order=(2, 0, 1)) -> np.ndarray:
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int, is_color=True) -> np.ndarray:
    h, w = im.shape[:2]
    h0 = max((h - size) // 2, 0)
    w0 = max((w - size) // 2, 0)
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im: np.ndarray, size: int, is_color=True,
                rng=None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    h0 = rng.randint(0, max(h - size, 0) + 1)
    w0 = rng.randint(0, max(w - size, 0) + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im: np.ndarray, is_color=True) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color=True, mean=None,
                     rng=None) -> np.ndarray:
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if (rng or np.random).randint(2) == 1:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_image(path: str, is_color=True) -> np.ndarray:
    raise RuntimeError("image file loading requires a local image; this "
                       "environment uses synthetic dataset readers")


def load_and_transform(path, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(path, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
