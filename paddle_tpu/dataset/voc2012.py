"""VOC2012 segmentation readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/voc2012.py -- train()/test()/
val() yield (image CHW float, label HW int) segmentation pairs with
21 classes. Synthetic scenes: axis-aligned class rectangles whose
pixel statistics correlate with the class id, so segmentation models
learn.
"""
from __future__ import annotations

import numpy as np

CLASS_NUM = 21
TRAIN_SIZE = 256
TEST_SIZE = 64
_H = _W = 96


def _make_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = np.zeros((_H, _W), np.int32)
            img = rng.rand(3, _H, _W).astype("float32") * 0.2
            for _ in range(int(rng.randint(1, 4))):
                cls = int(rng.randint(1, CLASS_NUM))
                y0, x0 = rng.randint(0, _H // 2), rng.randint(0, _W // 2)
                h, w = rng.randint(8, _H // 2), rng.randint(8, _W // 2)
                label[y0:y0 + h, x0:x0 + w] = cls
                img[:, y0:y0 + h, x0:x0 + w] += cls / CLASS_NUM
            yield img, label

    return reader


def train():
    return _make_reader(TRAIN_SIZE, 401)


def test():
    return _make_reader(TEST_SIZE, 402)


def val():
    return _make_reader(TEST_SIZE, 403)
