"""CoNLL-2005 semantic-role-labeling readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/conll05.py — get_dict() returns
(word_dict, verb_dict, label_dict); test() yields 9 slots:
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids, mark, label).
"""
from __future__ import annotations

import numpy as np

_WORD_VOCAB = 44068
_VERB_VOCAB = 3162
_N_LABELS = 67

TEST_SIZE = 1024


def get_dict():
    word_dict = {"w%d" % i: i for i in range(_WORD_VOCAB)}
    verb_dict = {"v%d" % i: i for i in range(_VERB_VOCAB)}
    label_dict = {"L%d" % i: i for i in range(_N_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    return None


def _make_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = int(rng.randint(4, 40))
            words = rng.randint(0, _WORD_VOCAB, size=length)

            def ctx(shift):
                idx = np.clip(np.arange(length) + shift, 0, length - 1)
                return [int(w) for w in words[idx]]

            verb = int(rng.randint(0, _VERB_VOCAB))
            vpos = int(rng.randint(0, length))
            mark = [1 if i == vpos else 0 for i in range(length)]
            labels = [int(x) for x in rng.randint(0, _N_LABELS, length)]
            yield ([int(w) for w in words], ctx(-2), ctx(-1), ctx(0),
                   ctx(1), ctx(2), [verb] * length, mark, labels)

    return reader


def test():
    return _make_reader(TEST_SIZE, seed=107)
