"""Shared dataset utilities.

Parity: reference python/paddle/dataset/common.py (download cache, md5
check, reader conversion). This environment has no network egress, so
every dataset module in this package generates *deterministic synthetic*
data with the exact shapes/dtypes/vocab structure of the real dataset;
`download` is kept as an API surface that resolves to the local cache or
raises with a clear message.
"""
from __future__ import annotations

import hashlib
import os
from typing import Callable

DATA_HOME = os.path.expanduser(os.environ.get("PADDLE_TPU_DATA_HOME",
                                              "~/.cache/paddle_tpu/dataset"))


def must_mkdirs(path: str) -> None:
    os.makedirs(path, exist_ok=True)


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: str | None = None) -> str:
    """Resolve a dataset file from the local cache (no network egress)."""
    dirname = os.path.join(DATA_HOME, module_name)
    must_mkdirs(dirname)
    filename = os.path.join(dirname, url.split("/")[-1])
    if os.path.exists(filename) and (
            md5sum is None or md5file(filename) == md5sum):
        return filename
    raise RuntimeError(
        "dataset file %s is not in the local cache (%s) and this "
        "environment has no network access; synthetic readers do not "
        "require it" % (url, dirname))


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Split files among trainers; parity with reference common.py."""
    import glob

    def reader():
        flist = sorted(glob.glob(files_pattern))
        my = flist[trainer_id::trainer_count]
        for fn in my:
            if loader is None:
                with open(fn, "rb") as f:
                    yield f.read()
            else:
                for item in loader(fn):
                    yield item

    return reader


def convert(output_path: str, reader: Callable, line_count: int,
            name_prefix: str) -> None:
    """Serialize a reader's items into chunked recordio files via the
    native writer (parity: reference common.py convert -> recordio)."""
    import pickle

    from ..native import RecordIOWriter

    must_mkdirs(output_path)
    idx = 0
    items = []

    def flush():
        nonlocal idx, items
        if not items:
            return
        path = os.path.join(output_path,
                            "%s-%05d" % (name_prefix, idx))
        w = RecordIOWriter(path)
        for it in items:
            w.write(pickle.dumps(it))
        w.close()
        idx += 1
        items = []

    for item in reader():
        items.append(item)
        if len(items) >= line_count:
            flush()
    flush()
