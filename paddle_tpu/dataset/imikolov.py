"""PTB (imikolov) language-model readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/imikolov.py -- build_dict()
token -> id with '<unk>'/'<e>'/'<s>'; train/test(word_idx, n) yield
n-gram tuples (DataType.NGRAM) or (src_seq, trg_seq) next-word pairs
(DataType.SEQ). Synthetic corpus: a deterministic order-2 Markov chain
over the vocab so LM perplexity actually improves during training.
"""
from __future__ import annotations

import numpy as np

VOCAB_SIZE = 2074  # reference build_dict(min_word_freq=50) scale
TRAIN_SENTENCES = 2048
TEST_SENTENCES = 256


class DataType:
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq=50):
    # min_word_freq shapes the vocab like the reference's frequency
    # cutoff: the synthetic corpus has a fixed frequency profile, so
    # scale the vocab inversely with the cutoff (50 -> reference size)
    vocab = max(8, int(VOCAB_SIZE * 50 / max(int(min_word_freq), 1)))
    d = {"w%d" % i: i for i in range(vocab - 3)}
    d["<unk>"] = vocab - 3
    d["<s>"] = vocab - 2
    d["<e>"] = vocab - 1
    return d


def _sentences(n_sent, vocab, seed):
    rng = np.random.RandomState(seed)
    # deterministic sparse bigram table: each word strongly prefers a
    # few successors (so an LM has signal to learn)
    succ = rng.randint(0, vocab, size=(vocab, 4))
    for _ in range(n_sent):
        length = int(rng.randint(5, 25))
        w = int(rng.randint(0, vocab))
        sent = [w]
        for _ in range(length - 1):
            w = int(succ[w, rng.randint(0, 4)])
            sent.append(w)
        yield sent


def reader_creator(word_idx, n, data_type, n_sent, seed):
    vocab = len(word_idx) - 3
    bos = word_idx["<s>"]
    eos = word_idx["<e>"]

    def reader():
        for sent in _sentences(n_sent, vocab, seed):
            if DataType.NGRAM == data_type:
                l = [bos] + sent + [eos]
                if len(l) >= n:
                    l = np.asarray(l, dtype="int64")
                    for i in range(n, len(l) + 1):
                        yield tuple(l[i - n:i])
            elif DataType.SEQ == data_type:
                l = sent
                src_seq = [bos] + l
                trg_seq = l + [eos]
                yield src_seq, trg_seq
            else:
                raise ValueError(f"Unknown data type {data_type}")

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return reader_creator(word_idx, n, data_type, TRAIN_SENTENCES, 201)


def test(word_idx, n, data_type=DataType.NGRAM):
    return reader_creator(word_idx, n, data_type, TEST_SENTENCES, 202)
