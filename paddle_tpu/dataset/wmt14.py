"""WMT14 en-fr translation readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/wmt14.py — train(dict_size)
yields (src_ids, trg_ids, trg_next_ids) with <s>=0, <e>=1, <unk>=2.
Synthetic parallel corpus: target is a deterministic per-token mapping of
source (plus sentinels), so seq2seq models have learnable structure.
"""
from __future__ import annotations

import numpy as np

START = "<s>"
END = "<e>"
UNK = "<unk>"
START_ID, END_ID, UNK_ID = 0, 1, 2

TRAIN_SIZE = 2048
TEST_SIZE = 256


def _make_reader(dict_size, n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        shift = dict_size // 3
        for _ in range(n):
            length = int(rng.randint(4, 30))
            src = rng.randint(3, dict_size, size=length)
            trg = (src - 3 + shift) % (dict_size - 3) + 3
            trg_in = np.concatenate([[START_ID], trg])
            trg_next = np.concatenate([trg, [END_ID]])
            yield ([int(i) for i in src], [int(i) for i in trg_in],
                   [int(i) for i in trg_next])

    return reader


def train(dict_size):
    return _make_reader(dict_size, TRAIN_SIZE, seed=100)


def test(dict_size):
    return _make_reader(dict_size, TEST_SIZE, seed=101)


def get_dict(dict_size, reverse=False):
    src = {w: i for i, w in enumerate(
        [START, END, UNK] + ["src%d" % i for i in range(dict_size - 3)])}
    trg = {w: i for i, w in enumerate(
        [START, END, UNK] + ["trg%d" % i for i in range(dict_size - 3)])}
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
