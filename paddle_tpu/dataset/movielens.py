"""MovieLens-1M readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/movielens.py — items are
[user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
rating]; max_user_id/max_movie_id/... expose vocab sizes for embeddings.
"""
from __future__ import annotations

import numpy as np

_N_USERS = 6040
_N_MOVIES = 3952
_N_AGES = 7
_N_JOBS = 21
_N_CATEGORIES = 18
_TITLE_VOCAB = 5174

TRAIN_SIZE = 4096
TEST_SIZE = 512


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def age_table():
    return [1, 18, 25, 35, 45, 50, 56]


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {"cat%d" % i: i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    return {"t%d" % i: i for i in range(_TITLE_VOCAB)}


def _make_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            uid = int(rng.randint(1, _N_USERS + 1))
            mid = int(rng.randint(1, _N_MOVIES + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, _N_AGES))
            job = int(rng.randint(0, _N_JOBS))
            n_cat = int(rng.randint(1, 4))
            cats = [int(c) for c in rng.randint(0, _N_CATEGORIES, n_cat)]
            n_tit = int(rng.randint(1, 6))
            title = [int(t) for t in rng.randint(0, _TITLE_VOCAB, n_tit)]
            # deterministic preference structure for convergence
            score = 1.0 + 4.0 * (((uid * 2654435761 + mid * 40503) %
                                  1000) / 999.0)
            yield [uid, gender, age, job, mid, cats, title,
                   np.array([score], dtype=np.float32)]

    return reader


def train():
    return _make_reader(TRAIN_SIZE, seed=105)


def test():
    return _make_reader(TEST_SIZE, seed=106)
