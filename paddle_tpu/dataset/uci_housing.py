"""UCI housing regression readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/uci_housing.py — yields
(float32[13] features, float32[1] price); features are standardized.
A fixed linear ground truth + noise keeps fit_a_line convergence real.
"""
from __future__ import annotations

import numpy as np

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD",
    "TAX", "PTRATIO", "B", "LSTAT"
]

TRAIN_SIZE = 404
TEST_SIZE = 102

_W = np.random.RandomState(7).uniform(-2, 2, size=13).astype(np.float32)
_B = 22.5


def _make_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            x = rng.normal(0, 1, size=13).astype(np.float32)
            y = float(x @ _W + _B + rng.normal(0, 1.0))
            yield x, np.array([y], dtype=np.float32)

    return reader


def train():
    return _make_reader(TRAIN_SIZE, seed=96)


def test():
    return _make_reader(TEST_SIZE, seed=97)
