"""WMT16 en-de translation readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/wmt16.py — same item structure as
wmt14 but with configurable src/trg dict sizes and language direction.
"""
from __future__ import annotations

from . import wmt14

TRAIN_SIZE = 2048
TEST_SIZE = 256


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return wmt14._make_reader(min(src_dict_size, trg_dict_size),
                              TRAIN_SIZE, seed=102)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return wmt14._make_reader(min(src_dict_size, trg_dict_size),
                              TEST_SIZE, seed=103)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return wmt14._make_reader(min(src_dict_size, trg_dict_size),
                              TEST_SIZE, seed=104)


def get_dict(lang, dict_size, reverse=False):
    words = (["<s>", "<e>", "<unk>"] +
             ["%s%d" % (lang, i) for i in range(dict_size - 3)])
    d = {w: i for i, w in enumerate(words)}
    if reverse:
        d = {v: k for k, v in d.items()}
    return d
