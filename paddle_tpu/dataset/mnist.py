"""MNIST dataset readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/mnist.py — readers yield
(image, label) where image is a flat float32[784] scaled to [-1, 1] and
label an int in [0, 10). Zero-egress environment: images are generated
deterministically per (split, index) so loss curves are reproducible;
each class has a distinct mean pattern so small models actually learn.
"""
from __future__ import annotations

import numpy as np

TRAIN_SIZE = 8192
TEST_SIZE = 1024
IMG_DIM = 784


def _class_prototypes():
    rng = np.random.RandomState(1234)
    return rng.uniform(-0.6, 0.6, size=(10, IMG_DIM)).astype(np.float32)


_PROTOS = _class_prototypes()


def _make_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        labels = rng.randint(0, 10, size=n)
        for i in range(n):
            lab = int(labels[i])
            img = _PROTOS[lab] + rng.normal(
                0, 0.3, size=IMG_DIM).astype(np.float32)
            yield np.clip(img, -1.0, 1.0).astype(np.float32), lab

    return reader


def train():
    """Reader yielding (float32[784] in [-1,1], int label)."""
    return _make_reader(TRAIN_SIZE, seed=90)


def test():
    return _make_reader(TEST_SIZE, seed=91)


def fetch():
    return None
