"""MQ2007 learning-to-rank readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/mq2007.py -- readers in three
formats: pointwise (feature_vector, relevance), pairwise
(feature_left, feature_right) with left more relevant, listwise
(label_list, feature_list per query). 46 LETOR features; relevance in
{0,1,2}. Synthetic queries: a hidden linear scorer generates
consistent relevance so rankers converge.
"""
from __future__ import annotations

import numpy as np

FEATURE_DIM = 46
TRAIN_QUERIES = 128
TEST_QUERIES = 32


def _queries(n_query, seed):
    rng = np.random.RandomState(seed)
    w = rng.randn(FEATURE_DIM)
    for _ in range(n_query):
        n_doc = int(rng.randint(5, 20))
        feats = rng.rand(n_doc, FEATURE_DIM).astype("float32")
        score = feats @ w
        ranks = np.argsort(np.argsort(score))
        rel = (ranks * 3 // max(n_doc, 1)).astype("int64")  # 0..2
        yield feats, rel


def __reader__(n_query, seed, format="pairwise"):
    def pointwise():
        for feats, rel in _queries(n_query, seed):
            for f, r in zip(feats, rel):
                yield f, int(r)

    def pairwise():
        for feats, rel in _queries(n_query, seed):
            for i in range(len(rel)):
                for j in range(len(rel)):
                    if rel[i] > rel[j]:
                        yield feats[i], feats[j]

    def listwise():
        for feats, rel in _queries(n_query, seed):
            yield [int(r) for r in rel], [f for f in feats]

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train(format="pairwise"):
    return __reader__(TRAIN_QUERIES, 501, format=format)


def test(format="pairwise"):
    return __reader__(TEST_QUERIES, 502, format=format)
