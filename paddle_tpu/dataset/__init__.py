"""Dataset package (parity: reference python/paddle/dataset/).

All readers are deterministic synthetic generators with the real
datasets' shapes/vocabulary structure (zero-egress environment); see
common.py. Usage matches the reference:

    train_reader = paddle_tpu.batch(
        paddle_tpu.readers.shuffle(paddle_tpu.dataset.mnist.train(), 500),
        batch_size=128)
"""
from . import (cifar, common, conll05, flowers, image, imdb, imikolov,
               mnist, mq2007, sentiment, voc2012,
               movielens, uci_housing, wmt14, wmt16)

__all__ = ["mnist", "cifar", "imdb", "imikolov", "mq2007",
           "sentiment", "voc2012", "uci_housing", "movielens", "wmt14",
           "wmt16", "conll05", "flowers", "image", "common"]
