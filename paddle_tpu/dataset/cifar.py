"""CIFAR-10/100 readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/cifar.py — readers yield
(image, label); image is float32[3072] (3x32x32) scaled to [0, 1].
"""
from __future__ import annotations

import numpy as np

TRAIN_SIZE = 4096
TEST_SIZE = 512
IMG_DIM = 3 * 32 * 32


def _protos(n_classes, seed):
    rng = np.random.RandomState(seed)
    return rng.uniform(0.2, 0.8, size=(n_classes, IMG_DIM)).astype(np.float32)


_P10 = _protos(10, 10)
_P100 = _protos(100, 100)


def _make_reader(protos, n, seed):
    n_classes = protos.shape[0]

    def reader():
        rng = np.random.RandomState(seed)
        labels = rng.randint(0, n_classes, size=n)
        for i in range(n):
            lab = int(labels[i])
            img = protos[lab] + rng.normal(
                0, 0.15, size=IMG_DIM).astype(np.float32)
            yield np.clip(img, 0.0, 1.0).astype(np.float32), lab

    return reader


def train10():
    return _make_reader(_P10, TRAIN_SIZE, seed=92)


def test10():
    return _make_reader(_P10, TEST_SIZE, seed=93)


def train100():
    return _make_reader(_P100, TRAIN_SIZE, seed=94)


def test100():
    return _make_reader(_P100, TEST_SIZE, seed=95)
