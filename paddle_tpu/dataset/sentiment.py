"""NLTK movie-review sentiment readers (synthetic, deterministic).

Parity: reference python/paddle/dataset/sentiment.py -- get_word_dict()
sorted by frequency; train()/test() yield (word_id_list, label in
{0,1}) over the reference's 1600/400 train/test split
(NUM_TRAINING_INSTANCES of NUM_TOTAL_INSTANCES). Synthetic corpus reuses the imdb generator
at the movie_reviews corpus scale.
"""
from __future__ import annotations

from . import imdb as _imdb

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000
_VOCAB = 3000


def _word_idx():
    return {"w%d" % i: i for i in range(_VOCAB)}


def get_word_dict():
    return sorted(_word_idx().items(), key=lambda kv: kv[1])


def train():
    return _imdb._make_reader(_word_idx(), NUM_TRAINING_INSTANCES,
                              seed=301)


def test():
    return _imdb._make_reader(
        _word_idx(), NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES,
        seed=302)
