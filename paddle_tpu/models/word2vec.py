"""Word2vec skip-gram-era N-gram LM (reference tests/book/test_word2vec.py:
4-word context -> shared embeddings -> concat -> fc -> softmax)."""
from __future__ import annotations

from .. import layers
from ..param_attr import ParamAttr


def ngram_lm(words, dict_size, embed_size=32, hidden_size=256):
    """words: list of 4 context id vars + 1 target var."""
    embs = []
    for w in words[:-1]:
        emb = layers.embedding(
            w, size=[dict_size, embed_size],
            param_attr=ParamAttr(name="shared_w"))
        embs.append(emb)
    concat = layers.concat(embs, axis=1)
    hidden = layers.fc(concat, hidden_size, act="sigmoid")
    logits = layers.fc(hidden, dict_size)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, words[-1]))
    return loss, logits


def build_program(dict_size=1500, embed_size=32, hidden_size=256,
                  lr=0.001, with_optimizer=True):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        ws = [layers.data(n, shape=[1], dtype="int64")
              for n in ("firstw", "secondw", "thirdw", "fourthw",
                        "nextw")]
        loss, logits = ngram_lm(ws, dict_size, embed_size, hidden_size)
        if with_optimizer:
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return main, startup, loss
