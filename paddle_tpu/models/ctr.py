"""CTR model with high-dim sparse embeddings (reference
python/paddle/fluid/tests/unittests/dist_ctr.py + ctr_dataset_reader:
sparse id features -> embedding + sequence pooling -> fc tower -> ctc
binary softmax). BASELINE.md config 5's sparse/embedding path.
"""
from __future__ import annotations

from .. import layers
from ..layers.sequence import bind_seq_len


def ctr_dnn_model(dnn_ids, lr_ids, label, dnn_dict_dim=10001,
                  lr_dict_dim=10001, embedding_size=10,
                  layer_dims=(128, 64, 32, 1)):
    """dnn_ids/lr_ids: [B, T] int64 padded sparse features."""
    dnn_embedding = layers.embedding(
        dnn_ids, size=[dnn_dict_dim, embedding_size])
    bind_seq_len(dnn_embedding, dnn_ids)
    dnn_pool = layers.sequence_pool(dnn_embedding, "sum")
    dnn_out = dnn_pool
    for dim in layer_dims:
        dnn_out = layers.fc(dnn_out, dim, act="relu")
    lr_embedding = layers.embedding(lr_ids, size=[lr_dict_dim, 1])
    bind_seq_len(lr_embedding, lr_ids)
    lr_pool = layers.sequence_pool(lr_embedding, "sum")
    merge = layers.concat([dnn_out, lr_pool], axis=1)
    logits = layers.fc(merge, 2)
    predict = layers.softmax(logits)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(predict, label)
    auc_var, _ = layers.auc(predict, label)
    return loss, acc, auc_var, predict


def build_program(dnn_dict_dim=10001, lr_dict_dim=10001, lr=0.0001,
                  with_optimizer=True):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        dnn_ids = layers.data("dnn_data", shape=[-1], dtype="int64",
                              lod_level=1, append_batch_size=False)
        dnn_ids.shape = (-1, -1)
        lr_ids = layers.data("lr_data", shape=[-1], dtype="int64",
                             lod_level=1, append_batch_size=False)
        lr_ids.shape = (-1, -1)
        label = layers.data("click", shape=[1], dtype="int64")
        loss, acc, auc_var, predict = ctr_dnn_model(
            dnn_ids, lr_ids, label, dnn_dict_dim, lr_dict_dim)
        if with_optimizer:
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return main, startup, loss, auc_var
