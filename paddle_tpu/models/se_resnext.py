"""SE-ResNeXt-50 (reference benchmark/fluid/models/se_resnext.py)."""
from __future__ import annotations

from .. import layers


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_test=False):
    conv = layers.conv2d(input, num_filters, filter_size, stride=stride,
                         padding=(filter_size - 1) // 2, groups=groups,
                         bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test)


def squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = layers.pool2d(input, pool_type="avg", global_pooling=True)
    squeeze = layers.fc(pool, num_channels // reduction_ratio,
                        act="relu")
    excitation = layers.fc(squeeze, num_channels, act="sigmoid")
    return layers.elementwise_mul(input, excitation, axis=0)


def shortcut(input, ch_out, stride, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, is_test=is_test)
    return input


def bottleneck_block(input, num_filters, stride, cardinality=32,
                     reduction_ratio=16, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu",
                          is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act="relu",
                          is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, is_test=is_test)
    se = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, num_filters * 2, stride, is_test=is_test)
    return layers.relu(layers.elementwise_add(short, se))


def se_resnext50(input, class_dim=1000, is_test=False):
    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu",
                         is_test=is_test)
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2,
                         pool_padding=1, pool_type="max")
    for filters, count, stride0 in ((128, 3, 1), (256, 4, 2),
                                    (512, 6, 2), (1024, 3, 2)):
        for i in range(count):
            pool = bottleneck_block(
                pool, filters, stride0 if i == 0 else 1,
                is_test=is_test)
    pool = layers.pool2d(pool, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool, 0.5, is_test=is_test)
    return layers.fc(drop, class_dim)


def build_program(class_dim=1000, image_shape=(3, 224, 224), lr=0.1,
                  with_optimizer=True):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=list(image_shape),
                          dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        logits = se_resnext50(img, class_dim)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        if with_optimizer:
            fluid.optimizer.Momentum(lr, momentum=0.9).minimize(loss)
    return main, startup, loss
