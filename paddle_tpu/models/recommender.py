"""Personalized recommendation (reference tests/book/
test_recommender_system.py): user-tower and movie-tower embeddings ->
fc fusion -> cos_sim rating regression on MovieLens-shaped ids."""
from __future__ import annotations

from .. import layers
from ..layers.sequence import bind_seq_len

USR_DICT, GENDER_DICT, AGE_DICT, JOB_DICT = 6041, 2, 7, 21
MOV_DICT, CATEGORY_DICT, TITLE_DICT = 3953, 19, 5175


def user_tower():
    uid = layers.data("user_id", shape=[1], dtype="int64")
    usr_emb = layers.embedding(uid, size=[USR_DICT, 32])
    usr_fc = layers.fc(usr_emb, 32)

    gender = layers.data("gender_id", shape=[1], dtype="int64")
    gender_fc = layers.fc(
        layers.embedding(gender, size=[GENDER_DICT, 16]), 16)

    age = layers.data("age_id", shape=[1], dtype="int64")
    age_fc = layers.fc(layers.embedding(age, size=[AGE_DICT, 16]), 16)

    job = layers.data("job_id", shape=[1], dtype="int64")
    job_fc = layers.fc(layers.embedding(job, size=[JOB_DICT, 16]), 16)

    concat = layers.concat([usr_fc, gender_fc, age_fc, job_fc], axis=1)
    return layers.fc(concat, 200, act="tanh")


def movie_tower(title_len=8):
    mid = layers.data("movie_id", shape=[1], dtype="int64")
    mov_fc = layers.fc(layers.embedding(mid, size=[MOV_DICT, 32]), 32)

    # category and title are variable-length id lists (LoD in the
    # reference): padded + @SEQ_LEN here, pooled to fixed width
    cat = layers.data("category_id", shape=[CATEGORY_DICT],
                      dtype="int64")
    cat_emb = layers.embedding(cat, size=[CATEGORY_DICT, 32])
    bind_seq_len(cat_emb, cat)
    cat_pool = layers.sequence_pool(cat_emb, pool_type="sum")

    title = layers.data("movie_title", shape=[title_len],
                        dtype="int64")
    title_emb = layers.embedding(title, size=[TITLE_DICT, 32])
    bind_seq_len(title_emb, title)
    title_conv = layers.sequence_conv(title_emb, num_filters=32,
                                      filter_size=3, act="tanh")
    title_pool = layers.sequence_pool(title_conv, pool_type="sum")

    concat = layers.concat([mov_fc, cat_pool, title_pool], axis=1)
    return layers.fc(concat, 200, act="tanh")


def build_program(lr=0.2, with_optimizer=True, title_len=8):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        usr = user_tower()
        mov = movie_tower(title_len)
        scale_infer = layers.scale(layers.cos_sim(usr, mov), scale=5.0)
        label = layers.data("score", shape=[1], dtype="float32")
        cost = layers.mean(layers.square_error_cost(scale_infer,
                                                    label))
        if with_optimizer:
            fluid.optimizer.SGD(learning_rate=lr).minimize(cost)
    return main, startup, cost, scale_infer
