"""ResNet-50/101/152 (reference benchmark/fluid/models/resnet.py).

Built with the framework's own conv2d/batch_norm layers; bottleneck
topology matches the reference's so the benchmark exercises the same
conv/bn op mix. NCHW layout: XLA on TPU relayouts to its preferred
tiling internally.
"""
from __future__ import annotations

from .. import layers


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_test=False):
    conv = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        bias_attr=False)
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def shortcut(input, ch_out, stride, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, is_test=is_test)
    return input


def bottleneck_block(input, num_filters, stride, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu",
                          is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          act="relu", is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, act=None,
                          is_test=is_test)
    short = shortcut(input, num_filters * 4, stride, is_test=is_test)
    return layers.relu(layers.elementwise_add(short, conv2))


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False):
    cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3],
           152: [3, 8, 36, 3]}[depth]
    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu",
                         is_test=is_test)
    pool = layers.pool2d(conv, pool_size=3, pool_stride=2,
                         pool_padding=1, pool_type="max")
    filters = [64, 128, 256, 512]
    for stage, count in enumerate(cfg):
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            pool = bottleneck_block(pool, filters[stage], stride,
                                    is_test=is_test)
    pool = layers.pool2d(pool, pool_type="avg", global_pooling=True)
    logits = layers.fc(pool, class_dim)
    return logits


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False):
    """reference resnet.py resnet_cifar10: basic blocks, 3 stages."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6

    def basicblock(x, ch_out, stride):
        conv0 = conv_bn_layer(x, ch_out, 3, stride, act="relu",
                              is_test=is_test)
        conv1 = conv_bn_layer(conv0, ch_out, 3, 1, is_test=is_test)
        short = shortcut(x, ch_out, stride, is_test=is_test)
        return layers.relu(layers.elementwise_add(short, conv1))

    conv = conv_bn_layer(input, 16, 3, 1, act="relu", is_test=is_test)
    for ch, stride in ((16, 1), (32, 2), (64, 2)):
        for i in range(n):
            conv = basicblock(conv, ch, stride if i == 0 else 1)
    pool = layers.pool2d(conv, pool_type="avg", global_pooling=True)
    return layers.fc(pool, class_dim)


def build_program(depth=50, class_dim=1000, image_shape=(3, 224, 224),
                  lr=0.1, with_optimizer=True):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=list(image_shape),
                          dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        logits = resnet_imagenet(img, class_dim, depth)
        loss = layers.softmax_with_cross_entropy(logits, label)
        avg_loss = layers.mean(loss)
        if with_optimizer:
            fluid.optimizer.Momentum(learning_rate=lr,
                                     momentum=0.9).minimize(avg_loss)
    return main, startup, avg_loss
