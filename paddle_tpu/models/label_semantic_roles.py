"""Semantic role labeling (reference tests/book/
test_label_semantic_roles.py): 8-way feature embeddings -> stacked
bidirectional dynamic LSTM -> linear-chain CRF over the tag sequence."""
from __future__ import annotations

from .. import layers
from ..layers.sequence import bind_seq_len
from ..param_attr import ParamAttr

WORD_DICT, PRED_DICT, MARK_DICT, LABEL_DICT = 1000, 200, 2, 59
WORD_DIM, MARK_DIM, HIDDEN, DEPTH = 32, 5, 128, 4
FEATURES = ("word_data", "ctx_n2_data", "ctx_n1_data", "ctx_0_data",
            "ctx_p1_data", "ctx_p2_data")


def db_lstm(seq_len=16, depth=DEPTH, hidden_dim=HIDDEN):
    """reference db_lstm :53: shared word embeddings over 6 context
    features + predicate + mark, then `depth` alternating-direction
    LSTM layers with mix-hidden skip connections."""
    word_inputs = [layers.data(n, shape=[seq_len], dtype="int64")
                   for n in FEATURES]
    predicate = layers.data("verb_data", shape=[seq_len],
                            dtype="int64")
    mark = layers.data("mark_data", shape=[seq_len], dtype="int64")

    emb_layers = [layers.embedding(
        w, size=[WORD_DICT, WORD_DIM],
        param_attr=ParamAttr(name="emb", trainable=True))
        for w in word_inputs]
    emb_layers.append(layers.embedding(
        predicate, size=[PRED_DICT, WORD_DIM],
        param_attr=ParamAttr(name="vemb")))
    emb_layers.append(layers.embedding(
        mark, size=[MARK_DICT, MARK_DIM]))

    hidden_0 = layers.sums([
        layers.fc(emb, hidden_dim, num_flatten_dims=2)
        for emb in emb_layers])
    proj_0 = layers.fc(hidden_0, hidden_dim * 4, num_flatten_dims=2)
    bind_seq_len(proj_0, word_inputs[0])
    lstm_0, _ = layers.dynamic_lstm(
        proj_0, size=hidden_dim * 4, candidate_activation="relu",
        gate_activation="sigmoid", cell_activation="sigmoid")
    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = layers.sums([
            layers.fc(input_tmp[0], hidden_dim, num_flatten_dims=2),
            layers.fc(input_tmp[1], hidden_dim, num_flatten_dims=2)])
        proj = layers.fc(mix_hidden, hidden_dim * 4,
                         num_flatten_dims=2)
        bind_seq_len(proj, word_inputs[0])
        lstm, _ = layers.dynamic_lstm(
            proj, size=hidden_dim * 4, candidate_activation="relu",
            gate_activation="sigmoid", cell_activation="sigmoid",
            is_reverse=(i % 2) == 1)
        input_tmp = [mix_hidden, lstm]

    return layers.sums([
        layers.fc(input_tmp[0], LABEL_DICT, num_flatten_dims=2),
        layers.fc(input_tmp[1], LABEL_DICT, num_flatten_dims=2)])


def build_program(seq_len=16, lr=0.01, with_optimizer=True,
                  depth=DEPTH):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    from ..layers.sequence import seq_len_of

    with fluid.program_guard(main, startup):
        feature_out = db_lstm(seq_len=seq_len, depth=depth)
        target = layers.data("target", shape=[seq_len], dtype="int64")
        # lengths matter: padded positions must not contribute to the
        # CRF NLL nor receive decoded tags (reference LoD-aware CRF)
        length = seq_len_of(target)
        crf_cost = layers.linear_chain_crf(
            input=feature_out, label=target, length=length,
            param_attr=ParamAttr(name="crfw", learning_rate=1.0))
        avg_cost = layers.mean(crf_cost)
        crf_decode = layers.crf_decoding(
            input=feature_out, length=length,
            param_attr=ParamAttr(name="crfw"))
        if with_optimizer:
            fluid.optimizer.SGD(learning_rate=lr).minimize(avg_cost)
    return main, startup, avg_cost, crf_decode
