"""VGG-16 (reference benchmark/fluid/models/vgg.py vgg16_bn_drop)."""
from __future__ import annotations

from .. import layers, nets


def vgg16_bn_drop(input, class_dim=1000, is_test=False):
    def conv_block(ipt, num_filter, groups):
        return nets.img_conv_group(
            input=ipt, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act="relu", conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=0.0, pool_type="max")

    conv1 = conv_block(input, 64, 2)
    conv2 = conv_block(conv1, 128, 2)
    conv3 = conv_block(conv2, 256, 3)
    conv4 = conv_block(conv3, 512, 3)
    conv5 = conv_block(conv4, 512, 3)
    drop = layers.dropout(conv5, 0.5, is_test=is_test)
    fc1 = layers.fc(drop, 512)
    bn = layers.batch_norm(fc1, act="relu", is_test=is_test,
                           data_layout="NHWC")
    drop2 = layers.dropout(bn, 0.5, is_test=is_test)
    fc2 = layers.fc(drop2, 512)
    return layers.fc(fc2, class_dim)


def build_program(class_dim=10, image_shape=(3, 32, 32), lr=0.01,
                  with_optimizer=True):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=list(image_shape),
                          dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        logits = vgg16_bn_drop(img, class_dim)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        if with_optimizer:
            fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, loss
