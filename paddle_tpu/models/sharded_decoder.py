"""tp-sharded decoder-step fixture: the sharded-serving entry proof.

ONE tensor-parallel ``cached_decoder_step`` program — the exact step
body the slot-pool serving stack dispatches (models/decode_engine.py)
— annotated with the Megatron-LM layout (Shoeybi et al.: column-
parallel qkv/fc1, row-parallel out/fc2, vocab-parallel logits head,
self/cross KV sharded along heads) on a named dp x tp mesh. The
annotations are EXACTLY the surface PR 13's sharded serving lowerings
will emit (absint.mark_sharded placements + absint.set_mesh); nothing
in the engine changes — this module only marks the already-built step
program, so the sharded lowerings inherit a prover and a memory
planner that are already green on the real program shape:

* the sharding domain propagates the head-sharded layout through the
  cached attention (scores/context ride ``{1: tp}``, the row-parallel
  out-projections imply the psum over ``tp`` exactly where Megatron
  places it), and the strict lint zoo pins the whole fixture
  error-free (analysis/targets.py ``sharded_decoder`` target);
* the PTA170 planner prices the per-device KV state at ~1/tp of the
  unsharded bundle — the ROADMAP's "per-device KV bytes shrinking
  ~1/tp via memory_analysis()" claim as a machine-checked number
  (tests/test_memory_plan.py);
* the baseline's ``sharding_facts`` section snapshots the propagated
  specs, so any drift in the propagation rules shows up as a CI diff
  instead of a silently different layout.

Reference counterpart: none — the reference sharded at runtime via
transpilers (reference transpiler/distribute_transpiler.py); a
statically-annotated, statically-proven tensor-parallel decode step
is the GSPMD-era capability this repo builds toward.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from .. import unique_name
from ..analysis import absint

__all__ = ["ShardedDecoderFixture", "build_tp_sharded_decoder_step",
           "TP_AXIS", "DP_AXIS"]

DP_AXIS = "dp"
TP_AXIS = "tp"


@dataclass
class ShardedDecoderFixture:
    """The annotated step program plus everything tests need to
    assert the sharding story: the un-annotated bundle it came from,
    the mesh, and the annotated name -> placement map."""
    program: object                 # the tp-annotated step program
    startup: object
    bundle: object                  # the DecodeStepBundle (dense)
    mesh: absint.MeshConfig
    placements: Dict[str, dict] = field(default_factory=dict)
    kv_names: List[str] = field(default_factory=list)

    def kv_state_bytes(self) -> int:
        """Unsharded KV bytes of the bundle's self+cross cache state
        (the denominator of the ~1/tp per-device claim)."""
        return self.bundle.kv_state_bytes()


def _annotate(block, placements, name, dims):
    var = block.vars.get(name)
    if var is None:
        var = block._find_var_recursive(name)
    if var is None:
        raise KeyError(f"sharded_decoder fixture: no var {name!r} in "
                       f"the step program")
    absint.mark_sharded(var, dims)
    placements[name] = dict(dims)
    return var


def build_tp_sharded_decoder_step(tp: int = 2, dp: int = 4,
                                  seq_len: int = 8,
                                  max_out_len: int = 8,
                                  d_model: int = 32, n_heads: int = 4,
                                  n_layers: int = 2,
                                  d_inner: int = 64, vocab: int = 64,
                                  n_slots: int = 4,
                                  state_prefix: str = "@tpfx/"
                                  ) -> ShardedDecoderFixture:
    """Build the dense decode-step bundle and annotate its step
    program with the Megatron tensor-parallel layout (annotations
    only — the builder is the stock
    transformer.build_decode_step_program)."""
    from . import transformer as T

    if n_heads % tp:
        raise ValueError(f"n_heads={n_heads} must divide over tp={tp}")
    with unique_name.guard():
        bundle = T.build_decode_step_program(
            seq_len=seq_len, max_out_len=max_out_len, d_model=d_model,
            n_heads=n_heads, n_layers=n_layers, d_inner=d_inner,
            vocab=vocab, n_slots=n_slots, state_prefix=state_prefix)
    step = bundle.step
    mesh = absint.MeshConfig.make(**{DP_AXIS: dp, TP_AXIS: tp})
    absint.set_mesh(step, mesh)
    blk = step.global_block
    placements: Dict[str, dict] = {}
    kv_names: List[str] = []
    # --- KV cache state: sharded along heads (dim 1 of the dense
    # [rows, H, T, Dh] per-lane buffers) — the paged analogue is the
    # ROADMAP's [n_blocks, block_size, H/tp, Dh] pool ---
    for name in bundle._state_specs:
        short = name.split("/")[-1]
        if short.startswith(("self_k", "self_v", "cross_k",
                             "cross_v")):
            _annotate(blk, placements, name, {1: TP_AXIS})
            kv_names.append(name)
    # --- decoder params: Megatron column/row-parallel pairs ---
    for li in range(n_layers):
        _annotate(blk, placements, f"dec{li}_self_qkv.w",
                  {1: TP_AXIS})      # column-parallel fused qkv
        _annotate(blk, placements, f"dec{li}_self_out.w",
                  {0: TP_AXIS})      # row-parallel out projection
        _annotate(blk, placements, f"dec{li}_cross_q.w",
                  {1: TP_AXIS})
        _annotate(blk, placements, f"dec{li}_cross_out.w",
                  {0: TP_AXIS})
        _annotate(blk, placements, f"dec{li}_fc1.w", {1: TP_AXIS})
        _annotate(blk, placements, f"dec{li}_fc2.w", {0: TP_AXIS})
    # --- vocab-parallel logits head (the Megatron output layer whose
    # branch-internal psum IS the 1F1B x tp rejection when it lands
    # under a divergent guard — here it sits in straight-line code,
    # which is exactly what the PTA161 proof requires) ---
    _annotate(blk, placements, "logits.w", {1: TP_AXIS})
    return ShardedDecoderFixture(step, bundle.startup, bundle, mesh,
                                 placements, kv_names)
