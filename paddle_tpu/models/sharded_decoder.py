"""tp-sharded decoder-step fixture — a thin wrapper over the REAL
sharded lowering.

Until PR 15 this module hand-annotated a stock dense bundle with a
prospective Megatron layout so the sharding prover and the per-device
memory planner could be built ahead of the feature. The sharded
serving lowering has now landed in the engine itself
(``models/decode_engine.ShardingConfig`` →
``build_decode_step_program(sharding=...)``), so this fixture simply
builds a tp-sharded bundle through the SHIPPED code path and exposes
its step program — the zoo target (analysis/targets.py
``sharded_decoder``) and the memory-plan tests lint/price the code
that actually serves, not a hand-built twin.

What the shipped layout pins (ShardingConfig docstring has the full
rationale):

* self/cross KV state sharded along heads (dim 1 of the dense
  ``[rows, H, maxT, Dh]`` lane buffers; the paged pools shard
  ``[n_blocks, block_size, H/tp, Dh]``) — per-device KV bytes exactly
  1/tp (tests/test_memory_plan.py);
* row-parallel attention out-projections + column/row-parallel ffn
  (their psums are the PTA161-proof obligations), column-parallel
  cross-attention query, vocab-sharded logits head;
* the fused self-attention qkv and the fused cross-KV projections
  REPLICATED (their fused-axis split crosses tp shard boundaries —
  sharding them would force a per-tick reshard, which PTA160 rejects
  inside the serve While).

Reference counterpart: none — the reference sharded at runtime via
transpilers (reference transpiler/distribute_transpiler.py); a
statically-annotated, statically-proven tensor-parallel decode step
is the GSPMD-era capability this repo builds toward.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from .. import unique_name
from ..analysis import absint

__all__ = ["ShardedDecoderFixture", "build_tp_sharded_decoder_step",
           "TP_AXIS", "DP_AXIS"]

DP_AXIS = "dp"
TP_AXIS = "tp"


@dataclass
class ShardedDecoderFixture:
    """The sharded step program plus everything tests need to assert
    the sharding story: the bundle it came from, the mesh, and the
    annotated name -> placement map."""
    program: object                 # the tp-annotated step program
    startup: object
    bundle: object                  # the tp-sharded DecodeStepBundle
    mesh: absint.MeshConfig
    placements: Dict[str, dict] = field(default_factory=dict)
    kv_names: List[str] = field(default_factory=list)

    def kv_state_bytes(self) -> int:
        """Unsharded KV bytes of the bundle's self+cross cache state
        (the denominator of the ~1/tp per-device claim)."""
        return self.bundle.kv_state_bytes()


def build_tp_sharded_decoder_step(tp: int = 2,
                                  seq_len: int = 8,
                                  max_out_len: int = 8,
                                  d_model: int = 32, n_heads: int = 4,
                                  n_layers: int = 2,
                                  d_inner: int = 64, vocab: int = 64,
                                  n_slots: int = 4,
                                  state_prefix: str = "@tpfx/"
                                  ) -> ShardedDecoderFixture:
    """Build a dense decode-step bundle through the REAL sharded
    lowering (``ShardingConfig(tp=tp)``) and expose its step program
    as the prover/planner fixture."""
    from . import transformer as T
    from .decode_engine import ShardingConfig

    with unique_name.guard():
        bundle = T.build_decode_step_program(
            seq_len=seq_len, max_out_len=max_out_len, d_model=d_model,
            n_heads=n_heads, n_layers=n_layers, d_inner=d_inner,
            vocab=vocab, n_slots=n_slots, state_prefix=state_prefix,
            sharding=ShardingConfig(tp=tp, axis=TP_AXIS))
    step = bundle.step
    placements = dict(bundle.sharding_plan.placements)
    kv_names = [
        name for name in bundle._state_specs
        if name.split("/")[-1].startswith(("self_k", "self_v",
                                           "cross_k", "cross_v"))]
    return ShardedDecoderFixture(step, bundle.startup, bundle,
                                 absint.mesh_of(step), placements,
                                 kv_names)
