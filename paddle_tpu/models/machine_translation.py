"""Seq2seq encoder-decoder NMT (reference benchmark/fluid/
machine_translation.py / tests/book/test_machine_translation.py:
GRU encoder -> attention-free decoder with teacher forcing)."""
from __future__ import annotations

from .. import layers
from ..layers.sequence import bind_seq_len


def seq_to_seq_net(src_ids, tgt_ids, label, src_dict_dim, tgt_dict_dim,
                   embedding_dim=512, encoder_size=512,
                   decoder_size=512):
    src_emb = layers.embedding(src_ids,
                               size=[src_dict_dim, embedding_dim])
    bind_seq_len(src_emb, src_ids)
    enc_proj = layers.fc(src_emb, encoder_size * 3, num_flatten_dims=2)
    bind_seq_len(enc_proj, src_emb)
    enc = layers.dynamic_gru(enc_proj, encoder_size)
    enc_last = layers.sequence_pool(enc, "last")

    tgt_emb = layers.embedding(tgt_ids,
                               size=[tgt_dict_dim, embedding_dim])
    bind_seq_len(tgt_emb, tgt_ids)
    dec_proj = layers.fc(tgt_emb, decoder_size * 3, num_flatten_dims=2)
    bind_seq_len(dec_proj, tgt_emb)
    dec_init = layers.fc(enc_last, decoder_size, act="tanh")
    dec = layers.dynamic_gru(dec_proj, decoder_size, h_0=dec_init)
    logits = layers.fc(dec, tgt_dict_dim, num_flatten_dims=2)
    loss = layers.mean(layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(label, [2])))
    return loss, logits


def build_program(src_dict_dim=10000, tgt_dict_dim=10000, lr=0.0002,
                  with_optimizer=True):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_word_id", shape=[-1], dtype="int64",
                          lod_level=1, append_batch_size=False)
        src.shape = (-1, -1)
        tgt = layers.data("target_language_word", shape=[-1],
                          dtype="int64", lod_level=1,
                          append_batch_size=False)
        tgt.shape = (-1, -1)
        label = layers.data("target_language_next_word", shape=[-1],
                            dtype="int64", lod_level=1,
                            append_batch_size=False)
        label.shape = (-1, -1)
        loss, logits = seq_to_seq_net(src, tgt, label, src_dict_dim,
                                      tgt_dict_dim)
        if with_optimizer:
            fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, loss
