"""Seq2seq encoder-decoder NMT (reference benchmark/fluid/
machine_translation.py / tests/book/test_machine_translation.py:
GRU encoder -> attention-free decoder with teacher forcing, plus the
beam-search inference decoder the book test builds from
contrib/decoder/beam_search_decoder.py).

All decoder-path parameters are NAMED so the training program and the
beam-decode program share weights through the scope (the reference
shares them the same way, by param name).
"""
from __future__ import annotations

from .. import layers
from ..layers.sequence import bind_seq_len

_P = {
    "src_emb": "mt_src_emb_w",
    "enc_proj_w": "mt_enc_proj_w", "enc_proj_b": "mt_enc_proj_b",
    "enc_gru_w": "mt_enc_gru_w", "enc_gru_b": "mt_enc_gru_b",
    "dec_boot_w": "mt_dec_boot_w", "dec_boot_b": "mt_dec_boot_b",
    "tgt_emb": "mt_tgt_emb_w",
    "dec_proj_w": "mt_dec_proj_w", "dec_proj_b": "mt_dec_proj_b",
    "dec_gru_w": "mt_dec_gru_w", "dec_gru_b": "mt_dec_gru_b",
    "softmax_w": "mt_softmax_w", "softmax_b": "mt_softmax_b",
}


def _encode(src_ids, src_dict_dim, embedding_dim, encoder_size):
    src_emb = layers.embedding(src_ids,
                               size=[src_dict_dim, embedding_dim],
                               param_attr=_P["src_emb"])
    bind_seq_len(src_emb, src_ids)
    enc_proj = layers.fc(src_emb, encoder_size * 3, num_flatten_dims=2,
                         param_attr=_P["enc_proj_w"],
                         bias_attr=_P["enc_proj_b"])
    bind_seq_len(enc_proj, src_emb)
    enc = layers.dynamic_gru(enc_proj, encoder_size,
                             param_attr=_P["enc_gru_w"],
                             bias_attr=_P["enc_gru_b"])
    enc_last = layers.sequence_pool(enc, "last")
    return enc, enc_last


def seq_to_seq_net(src_ids, tgt_ids, label, src_dict_dim, tgt_dict_dim,
                   embedding_dim=512, encoder_size=512,
                   decoder_size=512):
    enc, enc_last = _encode(src_ids, src_dict_dim, embedding_dim,
                            encoder_size)
    dec_init = layers.fc(enc_last, decoder_size, act="tanh",
                         param_attr=_P["dec_boot_w"],
                         bias_attr=_P["dec_boot_b"])

    tgt_emb = layers.embedding(tgt_ids,
                               size=[tgt_dict_dim, embedding_dim],
                               param_attr=_P["tgt_emb"])
    bind_seq_len(tgt_emb, tgt_ids)
    dec_proj = layers.fc(tgt_emb, decoder_size * 3, num_flatten_dims=2,
                         param_attr=_P["dec_proj_w"],
                         bias_attr=_P["dec_proj_b"])
    bind_seq_len(dec_proj, tgt_emb)
    dec = layers.dynamic_gru(dec_proj, decoder_size, h_0=dec_init,
                             param_attr=_P["dec_gru_w"],
                             bias_attr=_P["dec_gru_b"])
    logits = layers.fc(dec, tgt_dict_dim, num_flatten_dims=2,
                       param_attr=_P["softmax_w"],
                       bias_attr=_P["softmax_b"])
    loss = layers.mean(layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(label, [2])))
    return loss, logits


def build_program(src_dict_dim=10000, tgt_dict_dim=10000, lr=0.0002,
                  with_optimizer=True, embedding_dim=512,
                  encoder_size=512, decoder_size=512):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_word_id", shape=[-1], dtype="int64",
                          lod_level=1, append_batch_size=False)
        src.shape = (-1, -1)
        tgt = layers.data("target_language_word", shape=[-1],
                          dtype="int64", lod_level=1,
                          append_batch_size=False)
        tgt.shape = (-1, -1)
        label = layers.data("target_language_next_word", shape=[-1],
                            dtype="int64", lod_level=1,
                            append_batch_size=False)
        label.shape = (-1, -1)
        loss, logits = seq_to_seq_net(src, tgt, label, src_dict_dim,
                                      tgt_dict_dim, embedding_dim,
                                      encoder_size, decoder_size)
        if with_optimizer:
            fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, loss


def build_decode_program(src_dict_dim=10000, tgt_dict_dim=10000,
                         embedding_dim=512, encoder_size=512,
                         decoder_size=512, beam_size=4, max_len=32,
                         start_id=0, end_id=1, src_len=None):
    """Beam-search inference program sharing the training weights by
    name (reference tests/book/test_machine_translation.py decode()
    over contrib BeamSearchDecoder). Decodes ONE source sequence at
    static [beam_size, ...] shapes; returns
    (program, startup, feeds, (translation_ids, translation_scores)).
    """
    import paddle_tpu as fluid
    from .. import contrib

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_word_id", shape=[-1], dtype="int64",
                          lod_level=1, append_batch_size=False)
        src.shape = (1, src_len if src_len else -1)
        # static-batch program: declare the @SEQ_LEN companion at the
        # same concrete batch so build-time shape probes agree
        main.global_block.create_var(
            name="src_word_id@SEQ_LEN", shape=(1,), dtype="int32",
            is_data=True, stop_gradient=True)
        enc, enc_last = _encode(src, src_dict_dim, embedding_dim,
                                encoder_size)
        dec_boot = layers.fc(enc_last, decoder_size, act="tanh",
                             param_attr=_P["dec_boot_w"],
                             bias_attr=_P["dec_boot_b"])  # [1, H]
        h0 = layers.expand(dec_boot, [beam_size, 1])  # [beam, H]

        cell = contrib.StateCell(
            inputs={"word": None},
            states={"h": contrib.InitState(init=h0)},
            out_state="h")

        @cell.state_updater
        def updater(c):
            word = c.get_input("word")          # [beam, E]
            h_prev = c.get_state("h")           # [beam, H]
            proj = layers.fc(word, decoder_size * 3,
                             param_attr=_P["dec_proj_w"],
                             bias_attr=_P["dec_proj_b"])
            h, _, _ = layers.gru_unit(proj, h_prev, decoder_size * 3,
                                      param_attr=_P["dec_gru_w"],
                                      bias_attr=_P["dec_gru_b"])
            c.set_state("h", h)

        init_ids = layers.fill_constant([beam_size, 1], "int64",
                                        float(start_id))
        init_scores = layers.fill_constant([beam_size, 1], "float32",
                                           0.0)
        decoder = contrib.BeamSearchDecoder(
            cell, init_ids, init_scores,
            target_dict_dim=tgt_dict_dim, word_dim=embedding_dim,
            topk_size=min(50, tgt_dict_dim), max_len=max_len,
            beam_size=beam_size, end_id=end_id,
            name=_P["tgt_emb"],
            softmax_param_attr=_P["softmax_w"],
            softmax_bias_attr=_P["softmax_b"])
        out_ids, out_scores = decoder.decode()
    return (main, startup, ["src_word_id", "src_word_id@SEQ_LEN"],
            (out_ids, out_scores))
