"""MNIST models (reference benchmark/fluid/models/mnist.py cnn_model +
tests/book/test_recognize_digits.py)."""
from __future__ import annotations

from .. import layers, nets


def mlp(img, label, hidden_sizes=(128, 64)):
    h = img
    for size in hidden_sizes:
        h = layers.fc(h, size, act="relu")
    logits = layers.fc(h, 10)
    loss = layers.softmax_with_cross_entropy(logits, label)
    avg_loss = layers.mean(loss)
    acc = layers.accuracy(layers.softmax(logits), label)
    return avg_loss, acc, logits


def cnn_model(img, label):
    """LeNet-ish conv net (reference mnist.py cnn_model)."""
    conv1 = nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv2 = nets.simple_img_conv_pool(
        input=conv1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    logits = layers.fc(conv2, 10)
    loss = layers.softmax_with_cross_entropy(logits, label)
    avg_loss = layers.mean(loss)
    acc = layers.accuracy(layers.softmax(logits), label)
    return avg_loss, acc, logits


def build_program(batch_size=None, use_conv=True):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        if use_conv:
            img = layers.data("img", shape=[1, 28, 28], dtype="float32")
        else:
            img = layers.data("img", shape=[784], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        if use_conv:
            avg_loss, acc, logits = cnn_model(img, label)
        else:
            avg_loss, acc, logits = mlp(img, label)
    return main, startup, avg_loss, acc
