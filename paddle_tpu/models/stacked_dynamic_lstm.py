"""Stacked dynamic LSTM for PTB/IMDB-style language tasks
(reference benchmark/fluid/models/stacked_dynamic_lstm.py: embedding ->
N x (fc + dynamic_lstm) -> sequence max-pool -> fc -> softmax).

Variable-length sequences ride the padded+@SEQ_LEN representation
(layers/sequence.py); the LSTM time loop is one lax.scan per layer
(ops/rnn_ops.py), so the whole model is a single XLA program.
"""
from __future__ import annotations

from .. import layers
from ..layers.sequence import bind_seq_len


def stacked_lstm_net(sent_ids, label, dict_dim, emb_dim=512,
                     hid_dim=512, stacked_num=3, class_dim=2):
    emb = layers.embedding(sent_ids, size=[dict_dim, emb_dim])
    bind_seq_len(emb, sent_ids)

    fc1 = layers.fc(emb, hid_dim, num_flatten_dims=2)
    bind_seq_len(fc1, emb)
    lstm1, _ = layers.dynamic_lstm(fc1, hid_dim, use_peepholes=False)
    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = layers.fc(layers.concat(inputs, axis=2), hid_dim,
                       num_flatten_dims=2)
        bind_seq_len(fc, inputs[0])
        lstm, _ = layers.dynamic_lstm(fc, hid_dim, use_peepholes=False)
        inputs = [fc, lstm]
    fc_last = layers.sequence_pool(inputs[0], "max")
    lstm_last = layers.sequence_pool(inputs[1], "max")
    logits = layers.fc([fc_last, lstm_last], class_dim)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc


def build_program(dict_dim=10000, emb_dim=512, hid_dim=512,
                  stacked_num=3, class_dim=2, lr=0.002,
                  with_optimizer=True):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        sent = layers.data("words", shape=[-1], dtype="int64",
                           lod_level=1, append_batch_size=False)
        sent.shape = (-1, -1)
        label = layers.data("label", shape=[1], dtype="int64")
        loss, acc = stacked_lstm_net(sent, label, dict_dim, emb_dim,
                                     hid_dim, stacked_num, class_dim)
        if with_optimizer:
            fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, loss, acc
