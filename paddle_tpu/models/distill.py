"""Draft distillation — train a speculative DRAFT model on the
TARGET's own greedy outputs and softened logits.

Reference counterpart: the reference trains every model against task
labels only (tests/unittests/dist_transformer.py:1138 transformer
training loop); distillation composes the same program machinery — a
teacher-forced forward of BOTH models in one program, soft-label
``softmax_with_cross_entropy`` (operators/softmax_with_cross_entropy_
op.cc:32 documents the soft_label path) — into the loop the reference
never built.

Why this exists (PERF.md "Speculative decoding"): task-training leaves
the draft's CONTENT tokens at chance agreement with the target — both
tiny models learn "emit EOS at the planted position" but their
pre-EOS distributions are independently noisy, so measured acceptance
collapses off the memorized pool.  The acceptance probability ``a``
IS the speculation win (threshold a > c_spec/c_1), and ``a`` is
maximized not by matching the DATA but by matching the TARGET — which
is exactly the distillation objective:

    loss = hard_w * CE(d_logits, argmax t_logits)            (greedy)
         + (1-hard_w) * T^2 * CE(d_logits/T, softmax(t_logits/T))

The teacher stream is the target's OWN greedy decode of real prompts
(not the task labels), so the draft learns the distribution it will
actually be verified against at serve time, including the target's
mistakes.  The whole loop is in-repo and CPU-cheap: teacher rollouts
come from the caller's decode program, gradients flow ONLY into the
``draft.prefix``-named params (the teacher probs are stop_gradient
and minimize() takes an explicit parameter_list), and the K inner
steps per rollout batch ride ``Executor.run_steps`` (one scan
dispatch instead of K host round-trips).
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers

__all__ = ["build_distill_program", "distill_draft"]


def build_distill_program(draft, *, seq_len, max_out_len, d_model,
                          n_heads, n_layers, d_inner, vocab,
                          temperature=2.0, hard_weight=0.5,
                          learning_rate=0.005):
    """Build the distillation training program: a teacher-forced
    TARGET forward (is_test, params shared by name with the serving
    bundle's scope) producing softened probs + greedy labels, and a
    DRAFT forward (``draft.prefix``-named params) trained against
    both.  Returns ``(main, startup, loss, agree)`` where ``agree``
    fetches the per-batch argmax agreement — the in-program
    acceptance proxy (greedy spec acceptance IS argmax agreement on
    the accepted prefix).

    Feeds: ``src_ids`` [B, seq_len] and ``tgt_ids`` [B, max_out_len]
    — the teacher-forced decoder input, i.e. the target's own greedy
    stream shifted right behind ``start_id`` (see ``distill_draft``).

    Reference counterpart: tests/unittests/dist_transformer.py:1138
    (transformer train program assembly); the soft-label CE is
    operators/softmax_with_cross_entropy_op.cc:32.
    """
    from . import transformer as T

    if draft.kind != "model":
        raise ValueError("distillation needs a model draft "
                         f"(draft.kind={draft.kind!r})")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[seq_len], dtype="int64")
        tgt = layers.data("tgt_ids", shape=[max_out_len],
                          dtype="int64")

        def _forward(p, dm, nh, dinner):
            enc = T._embed(src, vocab, dm, max(seq_len, max_out_len),
                           0.0, True, f"{p}src_word_emb")
            for li in range(n_layers):
                enc = T.encoder_layer(enc, dm, nh, dinner, 0.0, True,
                                      name=f"{p}enc{li}")
            dec = T._embed(tgt, vocab, dm, max(seq_len, max_out_len),
                           0.0, True, f"{p}tgt_word_emb")
            for li in range(n_layers):
                dec = T.decoder_layer(dec, enc, dm, nh, dinner, 0.0,
                                      True, name=f"{p}dec{li}")
            return layers.fc(dec, vocab, num_flatten_dims=2,
                             bias_attr=False,
                             param_attr=f"{p}logits.w")

        t_logits = _forward("", d_model, n_heads, d_inner)
        d_logits = _forward(draft.prefix, draft.d_model,
                            draft.n_heads, draft.d_inner)
        # teacher signals are CONSTANTS to the backward pass: the
        # stop_gradient marks drop every target op from the grad op
        # path (backward.py _collect_no_grad), so only draft grads
        # are ever computed — not just ignored at apply time
        t_soft = layers.softmax(
            layers.scale(t_logits, scale=1.0 / float(temperature)))
        t_soft.stop_gradient = True
        t_hard = layers.cast(layers.argmax(t_logits, axis=-1),
                             "int64")
        t_hard.stop_gradient = True
        soft_ce = layers.softmax_with_cross_entropy(
            layers.scale(d_logits, scale=1.0 / float(temperature)),
            t_soft, soft_label=True)
        hard_ce = layers.softmax_with_cross_entropy(
            d_logits, layers.unsqueeze(t_hard, [2]))
        hw = float(hard_weight)
        # T^2 restores the soft term's gradient scale (Hinton et al.;
        # grads through softmax(z/T) shrink by 1/T^2)
        loss = layers.mean(layers.elementwise_add(
            layers.scale(hard_ce, scale=hw),
            layers.scale(soft_ce,
                         scale=(1.0 - hw) * float(temperature) ** 2)))
        agree = layers.mean(layers.cast(
            layers.equal(layers.cast(
                layers.argmax(d_logits, axis=-1), "int64"), t_hard),
            "float32"))
        draft_params = [p for p in main.all_parameters()
                        if p.name.startswith(draft.prefix)]
        if not draft_params:
            raise ValueError(
                f"no params under draft prefix {draft.prefix!r}")
        fluid.optimizer.Adam(learning_rate=learning_rate).minimize(
            loss, parameter_list=draft_params)
    return main, startup, loss, agree


def distill_draft(executor, scope, draft, decode_fn, prompts_fn, *,
                  seq_len, max_out_len, d_model, n_heads, n_layers,
                  d_inner, vocab, start_id, end_id, rounds=20,
                  batch=8, inner_steps=4, temperature=2.0,
                  hard_weight=0.5, learning_rate=0.005, seed=0):
    """Run the distillation loop against a live scope (the serving
    bundle's — target params are read in place, draft params are
    updated in place, so the NEXT server built on this scope serves
    the distilled draft with no copy step).

    ``decode_fn(srcs) -> [B, max_out_len] int64`` is the caller's
    greedy decode of the TARGET (the whole-loop oracle program or a
    server round-trip); ``prompts_fn(rng, n) -> [n, seq_len]`` draws
    training prompts.  Each round rolls out one teacher batch, then
    takes ``inner_steps`` optimizer steps on it as ONE
    ``Executor.run_steps`` scan dispatch.

    Returns a dict: per-round ``agree`` trajectory plus first/last —
    the before/after the PERF.md satellite records.

    Reference counterpart: tests/unittests/dist_transformer.py:1138
    (train loop); run_steps is core/executor.py:1081.
    """
    main, startup, loss, agree = build_distill_program(
        draft, seq_len=seq_len, max_out_len=max_out_len,
        d_model=d_model, n_heads=n_heads, n_layers=n_layers,
        d_inner=d_inner, vocab=vocab, temperature=temperature,
        hard_weight=hard_weight, learning_rate=learning_rate)
    # The startup program carries init ops for EVERY param the main
    # program declares — including the trained TARGET's.  Running it
    # straight into the live scope would silently re-randomize the
    # teacher (and the serving bundle reading the same scope), so run
    # it into a throwaway scope and copy over ONLY the vars the live
    # scope lacks (the draft's fresh Adam moments, typically).
    from ..core.scope import Scope

    tmp = Scope()
    executor.run(startup, scope=tmp)
    for name in tmp.local_var_names():
        have = scope.find_var(name)
        if have is not None and have.get_tensor().value() is not None:
            continue
        val = tmp.find_var(name).get_tensor().value()
        if val is not None:
            scope.var(name).get_tensor().set(val)
    rng = np.random.RandomState(seed)
    traj = []
    for _ in range(int(rounds)):
        srcs = np.asarray(prompts_fn(rng, batch), np.int64)
        out = np.asarray(decode_fn(srcs), np.int64)
        # sentinel-normalized rows (-1 after EOS) teacher-force as
        # end_id — the target's own post-EOS convention
        out = np.where(out < 0, end_id, out)
        tgt_in = np.concatenate(
            [np.full((len(srcs), 1), start_id, np.int64),
             out[:, :-1]], axis=1)
        feed = {"src_ids": srcs, "tgt_ids": tgt_in}
        fetched = executor.run_steps(
            main, feed=feed, fetch_list=[loss, agree],
            steps=int(inner_steps), scope=scope)
        # [K]-stacked fetches; keep the LAST inner step's agreement
        traj.append(float(np.asarray(fetched[1]).reshape(-1)[-1]))
    return {"agree": traj,
            "agree_first": traj[0] if traj else None,
            "agree_last": traj[-1] if traj else None}
