"""Model zoo mirroring the reference benchmark set
(reference benchmark/fluid/models/: mnist, resnet, vgg, se_resnext,
stacked_dynamic_lstm, machine_translation; + transformer from
tests/unittests/dist_transformer.py; + CTR from dist_ctr.py)."""
from . import mnist  # noqa: F401
from . import resnet  # noqa: F401
from . import vgg  # noqa: F401
from . import se_resnext  # noqa: F401
from . import transformer  # noqa: F401
from . import moe_transformer  # noqa: F401
from . import stacked_dynamic_lstm  # noqa: F401
from . import ctr  # noqa: F401
from . import word2vec  # noqa: F401
from . import machine_translation  # noqa: F401
from . import recommender  # noqa: F401
from . import label_semantic_roles  # noqa: F401
