"""MoE (Switch) transformer language model — the expert-parallel
flagship (VERDICT r3 weak #5: the MoE op/dataflow existed with no model
on top).

Beyond-reference capability (SURVEY.md §2.4 marks expert parallelism
ABSENT in Fluid); the *model-zoo* precedent is the reference's
benchmark transformer (reference benchmark/fluid/models/, tests/
unittests/dist_transformer.py), re-shaped as a decoder-only LM with a
Switch-Transformer FFN (Fedus et al. '21) on every other layer:

    embed -> L x [causal self-attn + (dense FFN | switch_moe FFN)]
          -> vocab logits -> label-smoothed CE
    cost = ce + aux_coeff * mean(per-layer Switch aux losses)

Every MoE layer also emits its drop fraction (tokens that received no
expert slot) as a fetchable `layerN_moe_drop` var — free when
unfetched. Under `with expert_parallel(mesh):` the switch_moe ops run
the all_to_all expert-parallel dataflow over the 'ep' mesh axis; the
alternating dense/MoE pair structure keeps the layer stack
period-2-isomorphic so the SAME program pipelines through
PipelineTrainer / a 'pp' CompiledProgram mesh.
"""
from __future__ import annotations

import numpy as np

from .. import layers
from ..param_attr import ParamAttr
from .transformer import (_add_norm, _embed, _ffn, multi_head_attention)


def moe_transformer(src_ids, label, vocab=32000, max_len=256,
                    d_model=512, n_heads=8, n_layers=4, d_inner=2048,
                    n_experts=8, top_k=1, capacity_factor=2.0,
                    dropout_rate=0.1, is_test=False,
                    label_smooth_eps=0.1, aux_coeff=0.01):
    """Returns (avg_cost, ce_cost, logits, aux_mean, drop_names).
    src_ids/label: [B, T] int64 (next-token targets). n_layers must be
    even: layers alternate dense-FFN / switch-MoE-FFN."""
    assert n_layers % 2 == 0, "n_layers must be even (dense/moe pairs)"
    x = _embed(src_ids, vocab, d_model, max_len, dropout_rate, is_test,
               "word_emb")
    auxes, drop_names = [], []
    for li in range(n_layers):
        name = f"layer{li}"
        attn = multi_head_attention(
            x, x, d_model, n_heads, dropout_rate, causal=True,
            is_test=is_test, name=f"{name}_self")
        x = _add_norm(attn, x, dropout_rate, is_test, name=f"{name}_a")
        if li % 2 == 1:
            ffn, aux, drop = layers.switch_moe(
                x, num_experts=n_experts, d_inner=d_inner,
                top_k=top_k, capacity_factor=capacity_factor,
                name=f"{name}_moe", return_drop_frac=True)
            auxes.append(aux)
            drop_names.append(drop.name)
        else:
            ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test,
                       name=name)
        x = _add_norm(ffn, x, dropout_rate, is_test, name=f"{name}_b")
    logits = layers.fc(x, vocab, num_flatten_dims=2, bias_attr=False,
                       param_attr="logits.w")
    ce = layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(label, [2]),
        label_smooth_eps=label_smooth_eps)
    ce_cost = layers.mean(ce)
    aux_mean = layers.scale(layers.sums(auxes), scale=1.0 / len(auxes))
    avg_cost = layers.elementwise_add(
        ce_cost, layers.scale(aux_mean, scale=aux_coeff))
    return avg_cost, ce_cost, logits, aux_mean, drop_names


def build_program(batch_size=None, seq_len=64, vocab=32000, d_model=512,
                  n_heads=8, n_layers=4, d_inner=2048, n_experts=8,
                  top_k=1, capacity_factor=2.0, dropout_rate=0.1,
                  learning_rate=2.0, warmup_steps=4000,
                  with_optimizer=True, aux_coeff=0.01):
    """Program-path builder mirroring models/transformer.build_program.
    Returns (main, startup, avg_cost)."""
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[seq_len], dtype="int64")
        label = layers.data("label", shape=[seq_len], dtype="int64")
        avg_cost, ce_cost, logits, aux_mean, drops = moe_transformer(
            src, label, vocab=vocab, max_len=max(seq_len, 64),
            d_model=d_model, n_heads=n_heads, n_layers=n_layers,
            d_inner=d_inner, n_experts=n_experts, top_k=top_k,
            capacity_factor=capacity_factor,
            dropout_rate=dropout_rate, aux_coeff=aux_coeff)
        if with_optimizer:
            lr = layers.learning_rate_scheduler.noam_decay(
                d_model, warmup_steps)
            if learning_rate != 1.0:
                lr = layers.scale(lr, scale=float(learning_rate))
            opt = fluid.optimizer.Adam(
                learning_rate=lr, beta1=0.9, beta2=0.997, epsilon=1e-9)
            opt.minimize(avg_cost)
    main._moe_drop_vars = drops
    main._moe_aux_var = aux_mean.name
    return main, startup, avg_cost
