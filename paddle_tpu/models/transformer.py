"""Transformer base (reference python/paddle/fluid/tests/unittests/
dist_transformer.py + the original benchmark config: WMT en-de base --
d_model=512, 8 heads, 6+6 layers, ffn 2048, Adam + noam decay).

Built entirely from the framework's own layers; attention goes through
the flash-attention path (ops/pallas/attention.py) when enabled, else
the jnp composition -- either way one XLA program per step with all
matmuls on the MXU in bf16-friendly shapes.
"""
from __future__ import annotations

import numpy as np

from .. import layers, unique_name
from ..initializer import NumpyArrayInitializer, XavierInitializer
from ..param_attr import ParamAttr

# fixed-name [1] int64 var holding the number of While iterations a
# decode program actually ran (early-exit observability; fetchable)
DECODE_STEPS_VAR = "@decode_steps"


def _position_encoding(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("float64")
    dim = np.arange(0, d_model, 2).astype("float64")
    angle = pos / np.power(10000.0, dim / d_model)
    enc = np.zeros((max_len, d_model), dtype="float32")
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


def _attn_proj_attr(name, tag, d_model):
    """Deterministic attention projection param (explicit Xavier fans:
    the fused qkv shape would otherwise shrink the init scale ~29%).
    Fully explicit names (no unique_name) make weight sharing between
    train/decode/incremental-decode builds order-independent."""
    return ParamAttr(
        name=f"{name}_{tag}.w" if name else
        unique_name.generate(f"attn_{tag}_proj.w"),
        initializer=XavierInitializer(fan_in=d_model,
                                      fan_out=d_model))


def multi_head_attention(q_in, kv_in, d_model, n_heads, dropout_rate,
                         causal=False, is_test=False, name=None):
    head_dim = d_model // n_heads

    # fused projections: XLA does NOT merge separate dots over the
    # same operand, so 3 (or 2) [*,512]x[512,512] matmuls become one
    # wider MXU-friendlier matmul, split after.
    def _proj_attr(tag):
        return _attn_proj_attr(name, tag, d_model)

    import os

    if (q_in is kv_in and not is_test and dropout_rate == 0.0
            and os.environ.get("PADDLE_TPU_FUSE_ATTN_BLOCK") == "1"):
        # not is_test: decode programs keep the unfused path (their
        # While-loop bodies and cache-friendly shapes are validated
        # against the op composition, not the pallas kernel)
        # whole-layer fused sub-layer (PERF.md MFU lever): same params
        # (names + Xavier fans), same math, ONE op — A/B against the
        # unfused path by flipping the env var
        return layers.attention_block(
            q_in, n_heads, causal=causal,
            param_attr_qkv=_proj_attr("qkv"),
            param_attr_out=f"{name}_out.w" if name else None,
            name=name)

    if q_in is kv_in:
        qkv = layers.fc(q_in, 3 * d_model, num_flatten_dims=2,
                        bias_attr=False, param_attr=_proj_attr("qkv"))
        q, k, v = layers.split(qkv, 3, dim=2)
    else:
        q = layers.fc(q_in, d_model, num_flatten_dims=2,
                      bias_attr=False, param_attr=_proj_attr("q"))
        kv = layers.fc(kv_in, 2 * d_model, num_flatten_dims=2,
                       bias_attr=False, param_attr=_proj_attr("kv"))
        k, v = layers.split(kv, 2, dim=2)

    def split_heads(x):
        # [B,T,H,D] stays put: attention(layout='bthd') batches over
        # heads in the dot_general instead of a physical transpose
        return layers.reshape(x, [0, 0, n_heads, head_dim])

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    ctx = layers.attention(q, k, v, causal=causal,
                           scale=head_dim ** -0.5,
                           dropout_rate=0.0 if is_test else dropout_rate,
                           layout="bthd")
    ctx = layers.reshape(ctx, [0, 0, d_model])
    return layers.fc(ctx, d_model, num_flatten_dims=2, bias_attr=False,
                     param_attr=f"{name}_out.w" if name else None)


def _ffn(x, d_model, d_inner, dropout_rate, is_test, name=None):
    import os

    if (not is_test and dropout_rate == 0.0
            and os.environ.get("PADDLE_TPU_FUSE_ATTN_BLOCK") == "1"):
        # the MLP half of the whole-layer fusion (same knob as the
        # attention block; same param names/init as the unfused path)
        return layers.ffn_block(
            x, d_inner,
            param_attr_fc1=f"{name}_fc1.w" if name else None,
            bias_attr_fc1=f"{name}_fc1.b" if name else None,
            param_attr_fc2=f"{name}_fc2.w" if name else None,
            bias_attr_fc2=f"{name}_fc2.b" if name else None,
            name=name)
    h = layers.fc(x, d_inner, num_flatten_dims=2, act="relu",
                  param_attr=f"{name}_fc1.w" if name else None,
                  bias_attr=f"{name}_fc1.b" if name else None)
    if dropout_rate and not is_test:
        h = layers.dropout(h, dropout_rate,
                           dropout_implementation="upscale_in_train")
    return layers.fc(h, d_model, num_flatten_dims=2,
                     param_attr=f"{name}_fc2.w" if name else None,
                     bias_attr=f"{name}_fc2.b" if name else None)


def _add_norm(x, residual, dropout_rate, is_test, name=None):
    if dropout_rate and not is_test:
        x = layers.dropout(x, dropout_rate,
                           dropout_implementation="upscale_in_train")
    return layers.layer_norm(layers.elementwise_add(x, residual),
                             begin_norm_axis=2,
                             param_attr=f"{name}_ln.w" if name else
                             None,
                             bias_attr=f"{name}_ln.b" if name else
                             None)


def encoder_layer(x, d_model, n_heads, d_inner, dropout_rate, is_test,
                  name=None):
    attn = multi_head_attention(x, x, d_model, n_heads, dropout_rate,
                                is_test=is_test,
                                name=f"{name}_self" if name else None)
    x = _add_norm(attn, x, dropout_rate, is_test,
                  name=f"{name}_a" if name else None)
    ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test,
               name=f"{name}" if name else None)
    return _add_norm(ffn, x, dropout_rate, is_test,
                     name=f"{name}_b" if name else None)


def decoder_layer(x, enc_out, d_model, n_heads, d_inner, dropout_rate,
                  is_test, name=None):
    self_attn = multi_head_attention(x, x, d_model, n_heads,
                                     dropout_rate, causal=True,
                                     is_test=is_test,
                                     name=f"{name}_self" if name
                                     else None)
    x = _add_norm(self_attn, x, dropout_rate, is_test,
                  name=f"{name}_a" if name else None)
    cross = multi_head_attention(x, enc_out, d_model, n_heads,
                                 dropout_rate, is_test=is_test,
                                 name=f"{name}_cross" if name
                                 else None)
    x = _add_norm(cross, x, dropout_rate, is_test,
                  name=f"{name}_b" if name else None)
    ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test,
               name=f"{name}" if name else None)
    return _add_norm(ffn, x, dropout_rate, is_test,
                     name=f"{name}_c" if name else None)


def _embed(ids, vocab_size, d_model, max_len, dropout_rate, is_test,
           emb_name):
    emb = layers.embedding(
        ids, size=[vocab_size, d_model],
        param_attr=ParamAttr(name=emb_name))
    emb = layers.scale(emb, scale=d_model ** 0.5)
    pos_table = _position_encoding(max_len, d_model)
    seq_len = emb.shape[1] if emb.shape[1] and emb.shape[1] > 0 \
        else max_len
    pos = layers.assign(pos_table[:seq_len])
    emb = layers.elementwise_add(emb, pos, axis=1)
    if dropout_rate and not is_test:
        emb = layers.dropout(emb, dropout_rate,
                             dropout_implementation="upscale_in_train")
    return emb


def transformer(src_ids, tgt_ids, label, src_vocab=30000, tgt_vocab=30000,
                max_len=256, d_model=512, n_heads=8, n_layers=6,
                d_inner=2048, dropout_rate=0.1, is_test=False,
                label_smooth_eps=0.1, checkpoints=None):
    """Returns (avg_cost, logits). src_ids/tgt_ids: [B,T] int64;
    label: [B,T] int64 (next-token targets). When `checkpoints` is a
    list, each layer output is appended to it (for
    RecomputeOptimizer-style activation checkpointing)."""
    ck = checkpoints
    enc = _embed(src_ids, src_vocab, d_model, max_len, dropout_rate,
                 is_test, "src_word_emb")
    for li in range(n_layers):
        enc = encoder_layer(enc, d_model, n_heads, d_inner,
                            dropout_rate, is_test, name=f"enc{li}")
        if ck is not None:
            ck.append(enc)
    dec = _embed(tgt_ids, tgt_vocab, d_model, max_len, dropout_rate,
                 is_test, "tgt_word_emb")
    for li in range(n_layers):
        dec = decoder_layer(dec, enc, d_model, n_heads, d_inner,
                            dropout_rate, is_test, name=f"dec{li}")
        if ck is not None:
            ck.append(dec)
    logits = layers.fc(dec, tgt_vocab, num_flatten_dims=2,
                       bias_attr=False, param_attr="logits.w")
    # fused smoothing: same math as one_hot+label_smooth+soft-label CE
    # but never materializes the [B,T,V] one-hot (HBM-bound at 32k vocab)
    cost = layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(label, [2]),
        label_smooth_eps=label_smooth_eps)
    avg_cost = layers.mean(cost)
    return avg_cost, logits


def build_program(batch_size=None, seq_len=64, d_model=512, n_heads=8,
                  n_layers=6, d_inner=2048, vocab=30000,
                  learning_rate=2.0, warmup_steps=4000,
                  with_optimizer=True, dropout_rate=0.1,
                  recompute=False):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[seq_len], dtype="int64")
        tgt = layers.data("tgt_ids", shape=[seq_len], dtype="int64")
        label = layers.data("label", shape=[seq_len], dtype="int64")
        ck = [] if recompute else None
        avg_cost, logits = transformer(
            src, tgt, label, src_vocab=vocab, tgt_vocab=vocab,
            max_len=max(seq_len, 256), d_model=d_model, n_heads=n_heads,
            n_layers=n_layers, d_inner=d_inner,
            dropout_rate=dropout_rate, checkpoints=ck)
        if with_optimizer:
            lr = layers.learning_rate_scheduler.noam_decay(
                d_model, warmup_steps)
            opt = fluid.optimizer.Adam(
                learning_rate=lr, beta1=0.9, beta2=0.997, epsilon=1e-9)
            if recompute:
                opt = fluid.optimizer.RecomputeOptimizer(opt)
                opt._set_checkpoints(ck)
            opt.minimize(avg_cost)
    return main, startup, avg_cost


def _step_logits(dec, positions, counter, vocab):
    """Select step t's hidden row BEFORE the vocab projection: a
    [rows,D]x[D,V] matmul instead of [rows,maxT,D]x[D,V] — identical
    logits, maxT-fold cheaper (shared by all decode builders)."""
    t_mask = layers.cast(layers.equal(positions, counter), "float32")
    step_hidden = layers.reduce_sum(
        layers.elementwise_mul(dec, layers.unsqueeze(t_mask, [1]),
                               axis=1), dim=1)
    return layers.fc(step_hidden, vocab, bias_attr=False,
                     param_attr="logits.w")


def _init_token_buffer(src, positions, max_out_len, start_id):
    """[B, maxT] int64 zeros with the start token at position 0 — the
    loop-carried decode buffer both decode builders share."""
    buf = layers.fill_constant_batch_size_like(
        src, [-1, max_out_len], "int64", 0.0)
    if start_id:
        start_col = layers.cast(
            layers.equal(positions,
                         layers.fill_constant([1], "int64", 0.0)),
            "int64")
        buf = layers.elementwise_add(
            buf, layers.cast(
                layers.scale(start_col, scale=float(start_id)),
                "int64"))
    return layers.assign(buf)


def _emit_token_step(src, step_logits, positions, tgt_buf, finished,
                     counter, limit, cond, max_out_len, end_id):
    """Shared decode-loop tail: greedy argmax, EOS freeze (finished
    rows keep emitting end_id), one-hot write at position t+1, counter
    bump, loop-condition refresh. Mutates tgt_buf/finished/counter/
    cond in place — keep BOTH decode builders on this helper so their
    token-for-token equivalence can't silently diverge.

    The refreshed condition carries an all-rows-finished early-exit
    term: once every row has emitted end_id the loop stops instead of
    spinning to max_out_len emitting frozen end_id rows. Positions
    past the exit step keep their zero init — callers that need the
    variable-length result go through apply_eos_sentinel
    (inference/serving.py), which normalizes everything after the
    first end_id to the -1 sentinel either way. Expressed with
    reduce_sum/elementwise_min/greater_than only, all inside the
    native xla_train kernel slice (FLAGS_native_build builds these
    programs too)."""
    tok = layers.cast(layers.argmax(step_logits, axis=-1), "int64")
    not_fin = layers.elementwise_sub(
        layers.fill_constant_batch_size_like(
            src, [-1], "int64", 1.0), finished)
    tok = layers.elementwise_add(
        layers.elementwise_mul(tok, not_fin),
        layers.cast(layers.scale(finished, scale=float(end_id)),
                    "int64"))
    layers.assign(
        layers.elementwise_max(
            finished,
            layers.cast(layers.equal(
                tok, layers.fill_constant([1], "int64",
                                          float(end_id))), "int64")),
        output=finished)
    next_mask = layers.cast(
        layers.equal(positions,
                     layers.increment(counter, 1, in_place=False)),
        "int64")
    keep = layers.elementwise_sub(
        layers.fill_constant([max_out_len], "int64", 1.0), next_mask)
    layers.assign(
        layers.elementwise_add(
            layers.elementwise_mul(tgt_buf, keep),
            layers.elementwise_mul(layers.unsqueeze(tok, [1]),
                                   next_mask)),
        output=tgt_buf)
    layers.increment(counter, 1)
    # continue while BOTH hold: steps remain (limit - counter > 0) AND
    # at least one row is unfinished (sum(1 - finished) > 0); min(a, b)
    # > 0 encodes the conjunction without logical ops
    unfinished = layers.reduce_sum(
        layers.elementwise_sub(
            layers.fill_constant_batch_size_like(
                src, [-1], "int64", 1.0), finished),
        keep_dim=True)
    layers.greater_than(
        layers.elementwise_min(
            layers.elementwise_sub(limit, counter), unfinished),
        layers.fill_constant([1], "int64", 0.0), cond=cond)


def build_greedy_decode_program(seq_len=16, max_out_len=16,
                                d_model=64, n_heads=4, n_layers=2,
                                d_inner=128, vocab=1000, start_id=0,
                                end_id=1):
    """Autoregressive greedy generation (reference
    tests/unittests/dist_transformer.py:1498 fast_decode — its
    while-op beam loop, at beam 1 — rebuilt as a lax.while_loop over
    the full decoder at static shapes: each step re-runs the
    causally-masked decoder on the [B, max_out_len] token buffer and
    writes position t+1 by a one-hot mask; positions past t are
    ignored by the causal mask, so no KV cache is needed for
    correctness — incremental caching is a perf upgrade, not a
    semantics change). Rows that emit end_id are frozen: every later
    position holds end_id, like the reference's early-finish
    handling.

    Weight sharing with a training program is by EXPLICIT param name
    (enc{i}_*/dec{i}_*/logits.w/…_word_emb) — build order and
    unique_name state are irrelevant.
    Returns (program, startup, feeds, out_ids_var).
    """
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[seq_len], dtype="int64")
        enc = _embed(src, vocab, d_model, max(seq_len, max_out_len),
                     0.0, True, "src_word_emb")
        for li in range(n_layers):
            enc = encoder_layer(enc, d_model, n_heads, d_inner, 0.0,
                                is_test=True, name=f"enc{li}")

        positions = layers.cast(layers.range(0, max_out_len, 1),
                                "int64")
        tgt_buf = _init_token_buffer(src, positions, max_out_len,
                                     start_id)
        # fixed-name counter so tests/benches can fetch the number of
        # loop iterations actually taken (the early-exit probe)
        counter = layers.fill_constant(
            [1], "int64", 0,
            out=main.global_block.create_var(
                name=DECODE_STEPS_VAR, shape=(1,), dtype="int64",
                stop_gradient=True))
        limit = layers.fill_constant([1], "int64",
                                     float(max_out_len - 1))
        finished = layers.assign(layers.fill_constant_batch_size_like(
            src, [-1], "int64", 0.0))  # [B]: 1 once EOS emitted
        cond = layers.less_than(counter, limit)
        w = layers.While(cond)
        with w.block():
            dec = _embed(tgt_buf, vocab, d_model,
                         max(seq_len, max_out_len), 0.0, True,
                         "tgt_word_emb")
            for li in range(n_layers):
                dec = decoder_layer(dec, enc, d_model, n_heads,
                                    d_inner, 0.0, is_test=True,
                                    name=f"dec{li}")
            step_logits = _step_logits(dec, positions, counter,
                                       vocab)  # [B, V]
            _emit_token_step(src, step_logits, positions, tgt_buf,
                             finished, counter, limit, cond,
                             max_out_len, end_id)
    return main, startup, ["src_ids"], tgt_buf


def _heads_of(x, t, n_heads, head_dim):
    """[R,t,H*D] -> [R,H,t,D] (the cached-attention head layout both
    KV-cached decode builders share)."""
    return layers.transpose(
        layers.reshape(x, [0, t, n_heads, head_dim]),
        perm=[0, 2, 1, 3])


def _cached_decoder_step(x, caches, cross_kv, write_mask, keep_mask,
                         att_bias, d_model, n_heads, d_inner):
    """One KV-cached decoder-stack step over a [R,1,D] row batch
    (reference tests/unittests/dist_transformer.py:1498 fast_decode's
    cached decoder, factored so the whole-loop incremental program and
    the slot-pool single-step program trace the IDENTICAL math — their
    token-for-token parity is structural, not coincidental).

    caches: per-layer (kc, vc) [R,H,maxT,Dh] vars, written in place at
    each row's position via `write_mask`/`keep_mask` (one-hot /
    complement over the maxT axis, any shape that broadcasts against
    the cache: [maxT,1] for a shared scalar counter, [R,1,maxT,1] for
    per-row slot counters). att_bias is the 0/-1e9 validity bias added
    to the [R,H,1,maxT] attention scores ([maxT] or [R,1,1,maxT]).
    cross_kv: per-layer (ck, cv) [R,H,S,Dh] encoder projections.
    Param names are the explicit dec{li}_* scheme shared with the
    training build. Returns the [R,1,D] hidden row after all layers.
    """
    head_dim = d_model // n_heads
    scale = head_dim ** -0.5
    for li in range(len(caches)):
        kc, vc = caches[li]
        # --- cached causal self-attention (fused qkv) ---
        qkv = layers.fc(
            x, 3 * d_model, num_flatten_dims=2, bias_attr=False,
            param_attr=_attn_proj_attr(f"dec{li}_self", "qkv",
                                       d_model))
        q, k, v = layers.split(qkv, 3, dim=2)
        qh = _heads_of(q, 1, n_heads, head_dim)
        kh = _heads_of(k, 1, n_heads, head_dim)
        vh = _heads_of(v, 1, n_heads, head_dim)
        new_kc = layers.elementwise_add(
            layers.elementwise_mul(kc, keep_mask),
            layers.elementwise_mul(kh, write_mask))
        new_vc = layers.elementwise_add(
            layers.elementwise_mul(vc, keep_mask),
            layers.elementwise_mul(vh, write_mask))
        layers.assign(new_kc, output=kc)
        layers.assign(new_vc, output=vc)
        scores = layers.scale(
            layers.matmul(qh, kc, transpose_y=True),
            scale=scale)  # [R,H,1,maxT]
        scores = layers.elementwise_add(scores, att_bias)
        probs = layers.softmax(scores, axis=-1)
        ctx = layers.matmul(probs, vc)
        ctx = layers.reshape(
            layers.transpose(ctx, perm=[0, 2, 1, 3]),
            [0, 1, d_model])  # [R,1,HD]
        attn_out = layers.fc(ctx, d_model, num_flatten_dims=2,
                             bias_attr=False,
                             param_attr=f"dec{li}_self_out.w")
        x = _add_norm(attn_out, x, 0.0, True, name=f"dec{li}_a")
        # --- cross attention against precomputed enc K/V ---
        q2 = layers.fc(
            x, d_model, num_flatten_dims=2, bias_attr=False,
            param_attr=_attn_proj_attr(f"dec{li}_cross", "q",
                                       d_model))
        q2h = _heads_of(q2, 1, n_heads, head_dim)
        ck, cv = cross_kv[li]
        s2 = layers.scale(
            layers.matmul(q2h, ck, transpose_y=True),
            scale=scale)  # [R,H,1,S]
        p2 = layers.softmax(s2, axis=-1)
        ctx2 = layers.reshape(
            layers.transpose(layers.matmul(p2, cv),
                             perm=[0, 2, 1, 3]),
            [0, 1, d_model])
        cross_out = layers.fc(
            ctx2, d_model, num_flatten_dims=2,
            bias_attr=False,
            param_attr=f"dec{li}_cross_out.w")
        x = _add_norm(cross_out, x, 0.0, True, name=f"dec{li}_b")
        # --- ffn ---
        ffn = _ffn(x, d_model, d_inner, 0.0, True, name=f"dec{li}")
        x = _add_norm(ffn, x, 0.0, True, name=f"dec{li}_c")
    return x


def build_incremental_decode_program(seq_len=16, max_out_len=16,
                                     d_model=64, n_heads=4,
                                     n_layers=2, d_inner=128,
                                     vocab=1000, start_id=0,
                                     end_id=1):
    """KV-cached autoregressive greedy generation — the incremental
    variant of build_greedy_decode_program (reference
    tests/unittests/dist_transformer.py:1498 fast_decode caches
    per-layer K/V the same way). Each step embeds ONE token, runs the
    decoder stack on that single row against cached self-attention
    K/V (written in place at position t) and precomputed
    cross-attention K/V, so per-step cost is O(maxT) instead of
    O(maxT^2) — token-for-token identical to the full-recompute
    program (asserted in tests).

    Weight sharing: the same explicit param names the training build
    and build_greedy_decode_program use — order-independent.

    Returns (program, startup, feeds, out_ids_var).
    """
    import paddle_tpu as fluid

    head_dim = d_model // n_heads
    maxT = max_out_len

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[seq_len], dtype="int64")
        enc = _embed(src, vocab, d_model, max(seq_len, maxT), 0.0,
                     True, "src_word_emb")
        for li in range(n_layers):
            enc = encoder_layer(enc, d_model, n_heads, d_inner, 0.0,
                                is_test=True, name=f"enc{li}")

        def _heads(x, t):  # [B,T,H*D] -> [B,H,T,D]
            return layers.transpose(
                layers.reshape(x, [0, t, n_heads, head_dim]),
                perm=[0, 2, 1, 3])

        # cross-attention K/V once per layer (explicitly named
        # dec{li}_cross_kv.w, shared with the training build)
        cross_kv = []
        for li in range(n_layers):
            kv = layers.fc(enc, 2 * d_model, num_flatten_dims=2,
                           bias_attr=False,
                           param_attr=_attn_proj_attr(
                               f"dec{li}_cross", "kv", d_model))
            k, v = layers.split(kv, 2, dim=2)
            cross_kv.append((_heads(k, seq_len), _heads(v, seq_len)))

        positions = layers.cast(layers.range(0, maxT, 1), "int64")
        posf = layers.cast(positions, "float32")
        pos_table = layers.assign(
            _position_encoding(max(seq_len, maxT), d_model)[:maxT])

        tgt_buf = _init_token_buffer(src, positions, maxT, start_id)
        # per-layer self-attn caches [B,H,maxT,D]
        caches = []
        for li in range(n_layers):
            kc = layers.assign(layers.fill_constant_batch_size_like(
                src, [-1, n_heads, maxT, head_dim], "float32", 0.0))
            vc = layers.assign(layers.fill_constant_batch_size_like(
                src, [-1, n_heads, maxT, head_dim], "float32", 0.0))
            caches.append((kc, vc))
        counter = layers.fill_constant(
            [1], "int64", 0,
            out=main.global_block.create_var(
                name=DECODE_STEPS_VAR, shape=(1,), dtype="int64",
                stop_gradient=True))
        limit = layers.fill_constant([1], "int64", float(maxT - 1))
        finished = layers.assign(layers.fill_constant_batch_size_like(
            src, [-1], "int64", 0.0))
        cond = layers.less_than(counter, limit)
        w = layers.While(cond)
        with w.block():
            # embed ONLY the current token
            t_mask = layers.cast(layers.equal(positions, counter),
                                 "float32")  # [maxT]
            cur_tok = layers.reduce_sum(
                layers.elementwise_mul(tgt_buf,
                                       layers.cast(t_mask, "int64")),
                dim=1, keep_dim=True)  # [B,1]
            x = layers.embedding(cur_tok, size=[vocab, d_model],
                                 param_attr=ParamAttr(
                                     name="tgt_word_emb"))
            # lookup_table squeezes the trailing 1 of [B,1] ids:
            # restore the time axis for the [B,1,D] step row
            x = layers.unsqueeze(x, [1])
            x = layers.scale(x, scale=d_model ** 0.5)
            pos_t = layers.reduce_sum(
                layers.elementwise_mul(
                    pos_table, layers.unsqueeze(t_mask, [1]), axis=0),
                dim=0)  # [D]
            x = layers.elementwise_add(x, pos_t)  # [B,1,D]

            # attention validity: cached positions <= t
            att_mask = layers.scale(
                layers.cast(layers.greater_than(
                    posf, layers.cast(counter, "float32")),
                    "float32"), scale=-1e9)  # [maxT] 0 keep / -1e9 drop

            # one-hot write column at cache position t (axis 2 of the
            # [B,H,maxT,Dh] caches) and its complement
            m2 = layers.unsqueeze(t_mask, [1])  # [maxT,1]
            keepc = layers.unsqueeze(
                layers.elementwise_sub(
                    layers.fill_constant([maxT], "float32", 1.0),
                    t_mask), [1])
            x = _cached_decoder_step(x, caches, cross_kv, m2, keepc,
                                     att_mask, d_model, n_heads,
                                     d_inner)

            step_logits = layers.fc(
                layers.reshape(x, [0, d_model]), vocab,
                bias_attr=False, param_attr="logits.w")  # [B,V]
            _emit_token_step(src, step_logits, positions, tgt_buf,
                             finished, counter, limit, cond, maxT,
                             end_id)
    return main, startup, ["src_ids"], tgt_buf


class DecodeStepBundle:
    """Program set for slot-pool continuous batching (reference
    tests/unittests/dist_transformer.py:1498 fast_decode is the decode
    loop; the slot-pool scheduling follows the iteration-level /
    paged-slot serving discipline of Orca (OSDI'22) and vLLM
    (SOSP'23), PAPERS.md).

    All per-slot decode state is PERSISTABLE scope state shared by the
    programs (KV cache slots, token buffers, per-slot step counters,
    finished/active lane masks — dense pre-allocated buffers written
    by one-hot scatter, the repo's loop-carried-history convention).
    The pool holds ``n_slots`` schedulable lanes plus ONE extra
    dustbin row (index ``n_slots``) that absorbs the padded rows of a
    bucketed admission batch — it decodes garbage harmlessly (every
    op is row-wise) and is never scheduled.

    * ``prefills[A]`` — one admission program per bucket size A
      (power-of-two ladder up to n_slots): feeds ``src_ids`` [A,
      seq_len] + ``slots`` [A] (dustbin index for padded rows); runs
      the encoder over the WHOLE admission batch, scatters each row's
      cross-attention K/V into its slot (a one-hot matmul scatter),
      resets the slots' self-attention KV rows / token buffers /
      counters, and raises their active flags. One dispatch admits up
      to A requests — admission cost does not scale per-request.
      ``prefill`` aliases the A=1 bucket.
    * ``step`` — no feeds; advances EVERY lane one token in one
      dispatch (embed each lane's current token, cached decoder stack
      via the shared ``_cached_decoder_step`` body, greedy emit with
      EOS freeze, per-lane counter bump, lane auto-deactivation on
      EOS or buffer exhaustion). Safe to scan K steps on device
      (``Executor.prepare(steps=K)``): every state var is read AND
      written, so the scan carry is fully populated.
    * ``serves[A]`` — the fused scheduler-cycle programs the
      continuous server actually dispatches: the bucket-A admission
      body (absent at A=0) followed by a While that runs the step
      body until ``n_steps`` ticks ran or the live-lane count drops
      to ``min_active`` (both fed as [1] int64). A whole
      admit+decode-burst cycle is ONE dispatch, and with
      min_active = live - 1 the loop hands control back the moment a
      lane retires — iteration-level scheduling with no zombie
      device ticks.

    ``state`` maps logical names ('tok_buf', 'step', 'finished',
    'active') to the scope var names; ``init_slot_state(scope)`` seeds
    the pool (zeros; finished=1 so idle lanes emit frozen end_id rows
    and never corrupt anything). The returned ``startup`` holds param
    initializers only — serving runs against an already-trained scope
    and must NOT run it (it would clobber the weights); slot state
    comes from ``init_slot_state``.

    Weight sharing: the explicit enc{i}_*/dec{i}_*/logits.w/…_word_emb
    names — order-independent with the train and whole-loop builds.
    """

    def __init__(self, prefills, step, serves, startup, state,
                 n_slots, seq_len, max_out_len, start_id, end_id):
        self.prefills = dict(prefills)   # bucket size A -> Program
        self.prefill = self.prefills[min(self.prefills)]
        self.step = step
        self.serves = dict(serves)       # admit bucket (0=none) -> Program
        self.startup = startup
        self.state = dict(state)
        self.n_slots = n_slots
        self.dustbin = n_slots           # the padded-admission row
        self.seq_len = seq_len
        self.max_out_len = max_out_len
        self.start_id = start_id
        self.end_id = end_id
        self._state_specs = {}

    def init_slot_state(self, scope):
        """Seed the pool state in `scope` (idle slots: finished=1,
        active=0 — they step harmlessly until admitted)."""
        for name, (shape, dt) in self._state_specs.items():
            if name == self.state["finished"]:
                scope._set(name, np.ones(shape, dt))
            else:
                scope._set(name, np.zeros(shape, dt))


def _slot_state_specs(prefix, n_slots, maxT, seq_len, n_heads,
                      head_dim, n_layers):
    specs = {
        f"{prefix}tok_buf": ((n_slots, maxT), "int64"),
        f"{prefix}step": ((n_slots,), "int64"),
        f"{prefix}finished": ((n_slots,), "int64"),
        f"{prefix}active": ((n_slots,), "int64"),
    }
    for li in range(n_layers):
        specs[f"{prefix}self_k{li}"] = (
            (n_slots, n_heads, maxT, head_dim), "float32")
        specs[f"{prefix}self_v{li}"] = (
            (n_slots, n_heads, maxT, head_dim), "float32")
        specs[f"{prefix}cross_k{li}"] = (
            (n_slots, n_heads, seq_len, head_dim), "float32")
        specs[f"{prefix}cross_v{li}"] = (
            (n_slots, n_heads, seq_len, head_dim), "float32")
    return specs


def _declare_slot_state(block, specs):
    """Declare the persistable slot-pool vars in a program's global
    block (both programs bind the SAME scope values by name). Concrete
    shapes + dtypes keep them carry-declarable (checker PTA090)."""
    return {name: block.create_var(name=name, shape=shape, dtype=dt,
                                   persistable=True,
                                   stop_gradient=True)
            for name, (shape, dt) in specs.items()}


def build_decode_step_program(seq_len=16, max_out_len=16, d_model=64,
                              n_heads=4, n_layers=2, d_inner=128,
                              vocab=1000, start_id=0, end_id=1,
                              n_slots=8, admit_buckets=None,
                              state_prefix="@cb/"):
    """Build the slot-pool continuous-batching bundle (bucketed
    admission prefills + single-step decode over ``n_slots``
    device-resident lanes) — see DecodeStepBundle. The step program's
    per-layer math IS build_incremental_decode_program's While body
    (`_cached_decoder_step`), with the scalar loop counter replaced by
    a per-lane counter vector (one-hot masks become 2-D), so a lane
    decodes token-for-token exactly what the whole-loop program would
    — the continuous server's parity invariant.

    ``admit_buckets`` bounds the admission specializations (default:
    power-of-two ladder 1,2,4,... capped at n_slots); padded rows of
    a bucket land on the dustbin lane.

    Returns a DecodeStepBundle.
    """
    import paddle_tpu as fluid

    head_dim = d_model // n_heads
    maxT = max_out_len
    rows = n_slots + 1  # + the dustbin lane for padded admissions
    if admit_buckets is None:
        admit_buckets, b = [], 1
        while b < n_slots:
            admit_buckets.append(b)
            b *= 2
        admit_buckets.append(n_slots)
    admit_buckets = sorted(set(int(a) for a in admit_buckets))
    if admit_buckets[0] < 1 or admit_buckets[-1] > n_slots:
        raise ValueError(
            f"admit_buckets {admit_buckets} must lie in "
            f"[1, n_slots={n_slots}]")
    specs = _slot_state_specs(state_prefix, rows, maxT, seq_len,
                              n_heads, head_dim, n_layers)

    # --- admission body: admit up to A prompts in ONE dispatch
    # (batched encoder + one-hot matmul scatter); traced into both the
    # standalone prefill programs and the fused serve programs -------
    def _admit_body(sv, A):
        src = layers.data("src_ids", shape=[A, seq_len],
                          dtype="int64", append_batch_size=False)
        slots = layers.data("slots", shape=[A], dtype="int64",
                            append_batch_size=False)
        enc = _embed(src, vocab, d_model, max(seq_len, maxT), 0.0,
                     True, "src_word_emb")
        for li in range(n_layers):
            enc = encoder_layer(enc, d_model, n_heads, d_inner,
                                0.0, is_test=True,
                                name=f"enc{li}")
        lane_range = layers.cast(layers.range(0, rows, 1),
                                 "int64")
        # [A, rows] one-hot per admitted prompt; padded rows all
        # point at the dustbin, whose scatter-sum is garbage by
        # design — min() clamps its multiplicity in the masks
        oh = layers.cast(
            layers.equal(lane_range,
                         layers.reshape(slots, [A, 1])),
            "float32")
        any_f = layers.elementwise_min(
            layers.reduce_sum(oh, dim=0),
            layers.fill_constant([rows], "float32", 1.0))
        any_i = layers.cast(any_f, "int64")
        keep_f = layers.elementwise_sub(
            layers.fill_constant([rows], "float32", 1.0), any_f)
        keep_i = layers.elementwise_sub(
            layers.fill_constant([rows], "int64", 1.0), any_i)
        keep4 = layers.reshape(keep_f, [rows, 1, 1, 1])
        ohT = layers.transpose(oh, perm=[1, 0])  # [rows, A]
        flat = n_heads * seq_len * head_dim
        for li in range(n_layers):
            kvp = layers.fc(enc, 2 * d_model, num_flatten_dims=2,
                            bias_attr=False,
                            param_attr=_attn_proj_attr(
                                f"dec{li}_cross", "kv", d_model))
            k, v = layers.split(kvp, 2, dim=2)
            kh = _heads_of(k, seq_len, n_heads, head_dim)
            vh = _heads_of(v, seq_len, n_heads, head_dim)
            for var, new in (
                    (sv[f"{state_prefix}cross_k{li}"], kh),
                    (sv[f"{state_prefix}cross_v{li}"], vh)):
                # one-hot matmul scatter: row a of `new` lands on
                # lane slots[a]; untouched lanes read 0 and keep
                # their old value through keep4
                scat = layers.reshape(
                    layers.matmul(ohT,
                                  layers.reshape(new, [A, flat])),
                    [rows, n_heads, seq_len, head_dim])
                layers.assign(layers.elementwise_add(
                    layers.elementwise_mul(var, keep4), scat),
                    output=var)
            for var in (sv[f"{state_prefix}self_k{li}"],
                        sv[f"{state_prefix}self_v{li}"]):
                layers.assign(layers.elementwise_mul(var, keep4),
                              output=var)
        # token buffer rows: start_id at position 0, zeros
        # elsewhere (identical init row for every admission)
        positions = layers.cast(layers.range(0, maxT, 1), "int64")
        start_col = layers.cast(
            layers.equal(positions,
                         layers.fill_constant([1], "int64", 0.0)),
            "int64")
        row_init = layers.cast(
            layers.scale(start_col, scale=float(start_id)),
            "int64")
        any_col = layers.reshape(any_i, [rows, 1])
        keep_col = layers.reshape(keep_i, [rows, 1])
        tok_buf = sv[f"{state_prefix}tok_buf"]
        layers.assign(layers.elementwise_add(
            layers.elementwise_mul(tok_buf, keep_col),
            layers.elementwise_mul(any_col, row_init)),
            output=tok_buf)
        stepv = sv[f"{state_prefix}step"]
        layers.assign(layers.elementwise_mul(stepv, keep_i),
                      output=stepv)
        fin = sv[f"{state_prefix}finished"]
        layers.assign(layers.elementwise_mul(fin, keep_i),
                      output=fin)
        act = sv[f"{state_prefix}active"]
        # the dustbin lane never activates: it must not hold the
        # serve While open nor count against min_active
        valid = layers.assign(
            (np.arange(rows) < n_slots).astype("int64"))
        layers.assign(layers.elementwise_add(
            layers.elementwise_mul(act, keep_i),
            layers.elementwise_mul(any_i, valid)), output=act)

    prefills = {}
    startup = None
    for A in admit_buckets:
        prog = fluid.Program()
        st = fluid.Program()
        with fluid.program_guard(prog, st):
            _admit_body(_declare_slot_state(prog.global_block, specs),
                        A)
        prefills[A] = prog
        startup = startup or st

    # --- the one-token step body over all lanes (shared by the
    # standalone step program and the fused serve programs' While) ---
    def _step_body(sv):
        tok_buf = sv[f"{state_prefix}tok_buf"]
        stepv = sv[f"{state_prefix}step"]
        fin = sv[f"{state_prefix}finished"]
        act = sv[f"{state_prefix}active"]
        positions = layers.cast(layers.range(0, maxT, 1), "int64")
        posf = layers.cast(positions, "float32")
        pos_table = layers.assign(
            _position_encoding(max(seq_len, maxT), d_model)[:maxT])
        step2 = layers.reshape(stepv, [rows, 1])           # [R,1]
        t_mask = layers.cast(layers.equal(positions, step2),
                             "float32")                    # [R,maxT]
        cur_tok = layers.reduce_sum(
            layers.elementwise_mul(tok_buf,
                                   layers.cast(t_mask, "int64")),
            dim=1, keep_dim=True)                          # [R,1]
        x = layers.embedding(cur_tok, size=[vocab, d_model],
                             param_attr=ParamAttr(
                                 name="tgt_word_emb"))     # [R,D]
        x = layers.unsqueeze(x, [1])                       # [R,1,D]
        x = layers.scale(x, scale=d_model ** 0.5)
        pos_t = layers.matmul(t_mask, pos_table)           # [R,D]
        x = layers.elementwise_add(x, layers.unsqueeze(pos_t, [1]))
        # per-lane attention validity + cache write masks
        att_bias = layers.reshape(
            layers.scale(layers.cast(layers.greater_than(
                posf, layers.cast(step2, "float32")), "float32"),
                scale=-1e9),
            [rows, 1, 1, maxT])
        write_mask = layers.reshape(t_mask, [rows, 1, maxT, 1])
        keep_mask = layers.reshape(
            layers.elementwise_sub(
                layers.fill_constant([rows, maxT], "float32", 1.0),
                t_mask),
            [rows, 1, maxT, 1])
        caches = [(sv[f"{state_prefix}self_k{li}"],
                   sv[f"{state_prefix}self_v{li}"])
                  for li in range(n_layers)]
        cross_kv = [(sv[f"{state_prefix}cross_k{li}"],
                     sv[f"{state_prefix}cross_v{li}"])
                    for li in range(n_layers)]
        x = _cached_decoder_step(x, caches, cross_kv, write_mask,
                                 keep_mask, att_bias, d_model,
                                 n_heads, d_inner)
        step_logits = layers.fc(
            layers.reshape(x, [0, d_model]), vocab,
            bias_attr=False, param_attr="logits.w")        # [R,V]
        # --- per-lane emit (the _emit_token_step tail, vectorized over
        # lane counters; same freeze/write semantics) ---
        tok = layers.cast(layers.argmax(step_logits, axis=-1),
                          "int64")                         # [R]
        ones_n = layers.fill_constant([rows], "int64", 1.0)
        not_fin = layers.elementwise_sub(ones_n, fin)
        tok = layers.elementwise_add(
            layers.elementwise_mul(tok, not_fin),
            layers.cast(layers.scale(fin, scale=float(end_id)),
                        "int64"))
        new_fin = layers.elementwise_max(
            fin, layers.cast(layers.equal(
                tok, layers.fill_constant([1], "int64",
                                          float(end_id))), "int64"))
        next2 = layers.reshape(
            layers.elementwise_add(stepv, ones_n), [rows, 1])
        next_mask = layers.cast(layers.equal(positions, next2),
                                "int64")                   # [R,maxT]
        keep_tok = layers.elementwise_sub(
            layers.fill_constant([rows, maxT], "int64", 1.0),
            next_mask)
        new_step = layers.elementwise_add(stepv, act)  # gate by lane
        layers.assign(layers.elementwise_add(
            layers.elementwise_mul(tok_buf, keep_tok),
            layers.elementwise_mul(next_mask,
                                   layers.reshape(tok, [rows, 1]))),
            output=tok_buf)
        layers.assign(new_step, output=stepv)
        # lanes auto-deactivate on EOS or buffer exhaustion — the
        # host retires a lane the moment its active flag drops
        room = layers.cast(layers.less_than(
            new_step, layers.fill_constant([1], "int64",
                                           float(maxT - 1))),
            "int64")                                       # [N]
        new_act = layers.elementwise_mul(
            layers.elementwise_mul(
                act, layers.elementwise_sub(ones_n, new_fin)),
            room)
        layers.assign(new_act, output=act)
        layers.assign(new_fin, output=fin)

    # --- standalone single-step program (one tick = one dispatch;
    # also the Executor.prepare(steps=K) scan target) ----------------
    step_prog = fluid.Program()
    with fluid.program_guard(step_prog, fluid.Program()):
        _step_body(_declare_slot_state(step_prog.global_block, specs))

    # --- fused serve programs: [bucketed admission +] a decode-burst
    # While — a WHOLE scheduler cycle (admit + burst) is ONE dispatch,
    # so the host overhead amortizes over A admissions and a burst of
    # tokens per lane. The loop exits when EITHER n_steps ticks ran
    # OR the live-lane count drops to min_active (both fed): with a
    # non-empty host queue the server sets min_active = live - 1, so
    # control returns the MOMENT a lane retires and its slot refills
    # — iteration-level scheduling with zero zombie ticks — while an
    # empty queue sets min_active = 0 and the burst drains the pool.
    # One specialization per admission bucket (A=0: no admission). ---
    def _build_serve(A):
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            sv = _declare_slot_state(prog.global_block, specs)
            if A > 0:
                _admit_body(sv, A)
            n_steps = layers.data("n_steps", shape=[1], dtype="int64",
                                  append_batch_size=False)
            min_active = layers.data("min_active", shape=[1],
                                     dtype="int64",
                                     append_batch_size=False)
            act = sv[f"{state_prefix}active"]
            k = layers.fill_constant([1], "int64", 0)

            def _serve_cond(cond=None):
                # ticks remain AND live lanes exceed the exit
                # threshold: min(a, b) > 0
                return layers.greater_than(
                    layers.elementwise_min(
                        layers.elementwise_sub(n_steps, k),
                        layers.elementwise_sub(
                            layers.reduce_sum(act, keep_dim=True),
                            min_active)),
                    layers.fill_constant([1], "int64", 0.0),
                    cond=cond)

            cond = _serve_cond()
            w = layers.While(cond)
            with w.block():
                _step_body(sv)
                layers.increment(k, 1)
                _serve_cond(cond=cond)
        return prog

    serves = {0: _build_serve(0)}
    for A in admit_buckets:
        serves[A] = _build_serve(A)

    state = {"tok_buf": f"{state_prefix}tok_buf",
             "step": f"{state_prefix}step",
             "finished": f"{state_prefix}finished",
             "active": f"{state_prefix}active"}
    bundle = DecodeStepBundle(prefills, step_prog, serves, startup,
                              state, n_slots, seq_len, maxT, start_id,
                              end_id)
    bundle._state_specs = {
        n: (shape, dt) for n, (shape, dt) in specs.items()}
    return bundle


def build_beam_decode_program(seq_len=16, max_out_len=16, d_model=64,
                              n_heads=4, n_layers=2, d_inner=128,
                              vocab=1000, start_id=0, end_id=1,
                              beam_size=4, batch_size=1):
    """Batched beam-search generation (reference
    tests/unittests/dist_transformer.py:1523 beam_search inside
    fast_decode). Beams ride the batch axis at static
    [batch*beam, maxT] shapes (batch-major blocks of beam rows, the
    beam_search op's row layout): every step runs the causally-masked
    decoder over all rows, expands per-source with the beam_search op
    (accumulated log-probs, EOS freezing), reorders each hypothesis'
    token history by absolute parent_idx, and backtracks with
    beam_search_decode.

    Weight sharing: the explicit enc{i}_*/dec{i}_*/logits.w names.
    Returns (program, startup, feeds, (sentence_ids
    [T, batch*beam], sentence_scores [batch*beam])).
    """
    import paddle_tpu as fluid

    maxT = max_out_len
    rows = batch_size * beam_size
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        # static-batch program so build-time probes agree with the
        # concrete [rows, ...] vars downstream
        src = layers.data("src_ids", shape=[batch_size, seq_len],
                          dtype="int64", append_batch_size=False)
        enc1 = _embed(src, vocab, d_model, max(seq_len, maxT), 0.0,
                      True, "src_word_emb")
        for li in range(n_layers):
            enc1 = encoder_layer(enc1, d_model, n_heads, d_inner, 0.0,
                                 is_test=True, name=f"enc{li}")
        # repeat each source's encoding beam_size times consecutively
        # ([B,S,D] -> [B,beam,S,D] -> [B*beam,S,D], batch-major rows)
        enc = layers.reshape(
            layers.expand(layers.unsqueeze(enc1, [1]),
                          [1, beam_size, 1, 1]),
            [rows, seq_len, d_model])

        positions = layers.cast(layers.range(0, maxT, 1), "int64")
        # per-hypothesis token history [rows, maxT], GO at position 0
        tgt_buf = layers.assign(layers.fill_constant(
            [rows, maxT], "int64", 0.0))
        if start_id:
            start_col = layers.cast(
                layers.equal(positions,
                             layers.fill_constant([1], "int64", 0.0)),
                "int64")
            tgt_buf = layers.assign(layers.elementwise_add(
                tgt_buf, layers.cast(
                    layers.scale(start_col, scale=float(start_id)),
                    "int64")))
        pre_ids = layers.assign(layers.fill_constant(
            [rows, 1], "int64", float(start_id)))
        # ONE live beam per source at step 0 (the reference's LoD
        # single-seed): identical rows with equal scores would make
        # per-block top-k pick beam_size copies of the same argmax and
        # the beams would never diverge (degenerate greedy)
        pre_scores = layers.assign(np.where(
            np.arange(rows) % beam_size == 0, 0.0,
            -1e9).astype("float32").reshape(rows, 1))
        # step buffers for the backtrack [maxT, rows, 1]
        ids_buf = layers.assign(layers.fill_constant(
            [maxT, rows, 1], "int64", float(end_id)))
        scores_buf = layers.assign(layers.fill_constant(
            [maxT, rows, 1], "float32", 0.0))
        parents_buf = layers.assign(layers.fill_constant(
            [maxT, rows, 1], "int64", 0.0))
        zero = layers.fill_constant([1], "int64", 0)
        ids_buf = layers.assign(layers.scatter(
            ids_buf, zero, layers.reshape(pre_ids, [1, rows, 1])))

        counter = layers.fill_constant([1], "int64", 0)
        limit = layers.fill_constant([1], "int64", float(maxT - 1))
        cond = layers.less_than(counter, limit)
        w = layers.While(cond)
        with w.block():
            dec = _embed(tgt_buf, vocab, d_model, max(seq_len, maxT),
                         0.0, True, "tgt_word_emb")
            for li in range(n_layers):
                dec = decoder_layer(dec, enc, d_model, n_heads,
                                    d_inner, 0.0, is_test=True,
                                    name=f"dec{li}")
            step_logits = _step_logits(dec, positions, counter,
                                       vocab)  # [rows, V]
            probs = layers.softmax(step_logits)  # [rows, V]
            topk_scores, topk_ids = layers.topk(
                probs, min(2 * beam_size, vocab))
            acc = layers.elementwise_add(layers.log(topk_scores),
                                         pre_scores)
            sel_ids, sel_scores, parent = layers.beam_search(
                pre_ids, pre_scores, topk_ids, acc,
                beam_size=beam_size, end_id=end_id,
                return_parent_idx=True)
            parent_flat = layers.reshape(parent, shape=[rows])
            # each surviving hypothesis inherits its parent's history
            layers.assign(layers.gather(tgt_buf, parent_flat),
                          output=tgt_buf)
            layers.increment(counter, 1)
            next_mask = layers.cast(layers.equal(positions, counter),
                                    "int64")
            keep = layers.elementwise_sub(
                layers.fill_constant([maxT], "int64", 1.0), next_mask)
            layers.assign(layers.elementwise_add(
                layers.elementwise_mul(tgt_buf, keep),
                layers.elementwise_mul(
                    layers.reshape(sel_ids, [rows, 1]),
                    next_mask)), output=tgt_buf)
            layers.assign(layers.scatter(
                ids_buf, counter,
                layers.reshape(sel_ids, [1, rows, 1])),
                output=ids_buf)
            layers.assign(layers.scatter(
                scores_buf, counter,
                layers.reshape(sel_scores, [1, rows, 1])),
                output=scores_buf)
            layers.assign(layers.scatter(
                parents_buf, counter,
                layers.reshape(parent, [1, rows, 1])),
                output=parents_buf)
            layers.assign(layers.reshape(sel_ids, [rows, 1]),
                          output=pre_ids)
            layers.assign(layers.reshape(sel_scores, [rows, 1]),
                          output=pre_scores)
            layers.less_than(counter, limit, cond=cond)
        out_ids, out_scores = layers.beam_search_decode(
            ids_buf, scores_buf, beam_size=beam_size, end_id=end_id,
            parents=parents_buf)
    return main, startup, ["src_ids"], (out_ids, out_scores)
