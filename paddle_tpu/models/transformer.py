"""Transformer base (reference python/paddle/fluid/tests/unittests/
dist_transformer.py + the original benchmark config: WMT en-de base --
d_model=512, 8 heads, 6+6 layers, ffn 2048, Adam + noam decay).

Built entirely from the framework's own layers; attention goes through
the flash-attention path (ops/pallas/attention.py) when enabled, else
the jnp composition -- either way one XLA program per step with all
matmuls on the MXU in bf16-friendly shapes.

Decode fronts: the whole-loop, incremental and slot-pool builders
below keep their public signatures but DELEGATE to
models/decode_engine.py — the single home for decode capabilities
(cache layouts incl. the paged KV block pool, step body, loop/burst/
exit policy, emission). New decode features land there once, not
three times.
"""
from __future__ import annotations

import numpy as np

from .. import layers, unique_name
from ..initializer import XavierInitializer
from ..param_attr import ParamAttr
from . import decode_engine
# re-exports: the decode surface moved to decode_engine; every
# existing call site (tests, benches, analysis targets) keeps
# importing it from here
from .decode_engine import (DECODE_STEPS_VAR, CacheConfig,  # noqa: F401
                            DecodeStepBundle, DraftConfig,
                            SamplingConfig,
                            build_beam_decode_program,
                            build_decode_step_program,
                            build_greedy_decode_program,
                            build_incremental_decode_program)


def _position_encoding(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("float64")
    dim = np.arange(0, d_model, 2).astype("float64")
    angle = pos / np.power(10000.0, dim / d_model)
    enc = np.zeros((max_len, d_model), dtype="float32")
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


def _attn_proj_attr(name, tag, d_model):
    """Deterministic attention projection param (explicit Xavier fans:
    the fused qkv shape would otherwise shrink the init scale ~29%).
    Fully explicit names (no unique_name) make weight sharing between
    train/decode/incremental-decode builds order-independent."""
    return ParamAttr(
        name=f"{name}_{tag}.w" if name else
        unique_name.generate(f"attn_{tag}_proj.w"),
        initializer=XavierInitializer(fan_in=d_model,
                                      fan_out=d_model))


def multi_head_attention(q_in, kv_in, d_model, n_heads, dropout_rate,
                         causal=False, is_test=False, name=None):
    head_dim = d_model // n_heads

    # fused projections: XLA does NOT merge separate dots over the
    # same operand, so 3 (or 2) [*,512]x[512,512] matmuls become one
    # wider MXU-friendlier matmul, split after.
    def _proj_attr(tag):
        return _attn_proj_attr(name, tag, d_model)

    import os

    if (q_in is kv_in and not is_test and dropout_rate == 0.0
            and os.environ.get("PADDLE_TPU_FUSE_ATTN_BLOCK") == "1"):
        # not is_test: decode programs keep the unfused path (their
        # While-loop bodies and cache-friendly shapes are validated
        # against the op composition, not the pallas kernel)
        # whole-layer fused sub-layer (PERF.md MFU lever): same params
        # (names + Xavier fans), same math, ONE op — A/B against the
        # unfused path by flipping the env var
        return layers.attention_block(
            q_in, n_heads, causal=causal,
            param_attr_qkv=_proj_attr("qkv"),
            param_attr_out=f"{name}_out.w" if name else None,
            name=name)

    if q_in is kv_in:
        qkv = layers.fc(q_in, 3 * d_model, num_flatten_dims=2,
                        bias_attr=False, param_attr=_proj_attr("qkv"))
        q, k, v = layers.split(qkv, 3, dim=2)
    else:
        q = layers.fc(q_in, d_model, num_flatten_dims=2,
                      bias_attr=False, param_attr=_proj_attr("q"))
        kv = layers.fc(kv_in, 2 * d_model, num_flatten_dims=2,
                       bias_attr=False, param_attr=_proj_attr("kv"))
        k, v = layers.split(kv, 2, dim=2)

    def split_heads(x):
        # [B,T,H,D] stays put: attention(layout='bthd') batches over
        # heads in the dot_general instead of a physical transpose
        return layers.reshape(x, [0, 0, n_heads, head_dim])

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    ctx = layers.attention(q, k, v, causal=causal,
                           scale=head_dim ** -0.5,
                           dropout_rate=0.0 if is_test else dropout_rate,
                           layout="bthd")
    ctx = layers.reshape(ctx, [0, 0, d_model])
    return layers.fc(ctx, d_model, num_flatten_dims=2, bias_attr=False,
                     param_attr=f"{name}_out.w" if name else None)


def _ffn(x, d_model, d_inner, dropout_rate, is_test, name=None):
    import os

    if (not is_test and dropout_rate == 0.0
            and os.environ.get("PADDLE_TPU_FUSE_ATTN_BLOCK") == "1"):
        # the MLP half of the whole-layer fusion (same knob as the
        # attention block; same param names/init as the unfused path)
        return layers.ffn_block(
            x, d_inner,
            param_attr_fc1=f"{name}_fc1.w" if name else None,
            bias_attr_fc1=f"{name}_fc1.b" if name else None,
            param_attr_fc2=f"{name}_fc2.w" if name else None,
            bias_attr_fc2=f"{name}_fc2.b" if name else None,
            name=name)
    h = layers.fc(x, d_inner, num_flatten_dims=2, act="relu",
                  param_attr=f"{name}_fc1.w" if name else None,
                  bias_attr=f"{name}_fc1.b" if name else None)
    if dropout_rate and not is_test:
        h = layers.dropout(h, dropout_rate,
                           dropout_implementation="upscale_in_train")
    return layers.fc(h, d_model, num_flatten_dims=2,
                     param_attr=f"{name}_fc2.w" if name else None,
                     bias_attr=f"{name}_fc2.b" if name else None)


def _add_norm(x, residual, dropout_rate, is_test, name=None):
    if dropout_rate and not is_test:
        x = layers.dropout(x, dropout_rate,
                           dropout_implementation="upscale_in_train")
    return layers.layer_norm(layers.elementwise_add(x, residual),
                             begin_norm_axis=2,
                             param_attr=f"{name}_ln.w" if name else
                             None,
                             bias_attr=f"{name}_ln.b" if name else
                             None)


def encoder_layer(x, d_model, n_heads, d_inner, dropout_rate, is_test,
                  name=None):
    attn = multi_head_attention(x, x, d_model, n_heads, dropout_rate,
                                is_test=is_test,
                                name=f"{name}_self" if name else None)
    x = _add_norm(attn, x, dropout_rate, is_test,
                  name=f"{name}_a" if name else None)
    ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test,
               name=f"{name}" if name else None)
    return _add_norm(ffn, x, dropout_rate, is_test,
                     name=f"{name}_b" if name else None)


def decoder_layer(x, enc_out, d_model, n_heads, d_inner, dropout_rate,
                  is_test, name=None):
    self_attn = multi_head_attention(x, x, d_model, n_heads,
                                     dropout_rate, causal=True,
                                     is_test=is_test,
                                     name=f"{name}_self" if name
                                     else None)
    x = _add_norm(self_attn, x, dropout_rate, is_test,
                  name=f"{name}_a" if name else None)
    cross = multi_head_attention(x, enc_out, d_model, n_heads,
                                 dropout_rate, is_test=is_test,
                                 name=f"{name}_cross" if name
                                 else None)
    x = _add_norm(cross, x, dropout_rate, is_test,
                  name=f"{name}_b" if name else None)
    ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test,
               name=f"{name}" if name else None)
    return _add_norm(ffn, x, dropout_rate, is_test,
                     name=f"{name}_c" if name else None)


def _embed(ids, vocab_size, d_model, max_len, dropout_rate, is_test,
           emb_name):
    emb = layers.embedding(
        ids, size=[vocab_size, d_model],
        param_attr=ParamAttr(name=emb_name))
    emb = layers.scale(emb, scale=d_model ** 0.5)
    pos_table = _position_encoding(max_len, d_model)
    seq_len = emb.shape[1] if emb.shape[1] and emb.shape[1] > 0 \
        else max_len
    pos = layers.assign(pos_table[:seq_len])
    emb = layers.elementwise_add(emb, pos, axis=1)
    if dropout_rate and not is_test:
        emb = layers.dropout(emb, dropout_rate,
                             dropout_implementation="upscale_in_train")
    return emb


def transformer(src_ids, tgt_ids, label, src_vocab=30000, tgt_vocab=30000,
                max_len=256, d_model=512, n_heads=8, n_layers=6,
                d_inner=2048, dropout_rate=0.1, is_test=False,
                label_smooth_eps=0.1, checkpoints=None,
                name_prefix=""):
    """Returns (avg_cost, logits). src_ids/tgt_ids: [B,T] int64;
    label: [B,T] int64 (next-token targets). When `checkpoints` is a
    list, each layer output is appended to it (for
    RecomputeOptimizer-style activation checkpointing).
    ``name_prefix`` prefixes EVERY parameter name (enc/dec layers,
    embeddings, logits) — how a speculative DRAFT model trains
    weights that co-reside with the target's in one scope without
    aliasing (decode_engine.DraftConfig.prefix; the PTA100
    contract)."""
    ck = checkpoints
    p = name_prefix
    enc = _embed(src_ids, src_vocab, d_model, max_len, dropout_rate,
                 is_test, f"{p}src_word_emb")
    for li in range(n_layers):
        enc = encoder_layer(enc, d_model, n_heads, d_inner,
                            dropout_rate, is_test, name=f"{p}enc{li}")
        if ck is not None:
            ck.append(enc)
    dec = _embed(tgt_ids, tgt_vocab, d_model, max_len, dropout_rate,
                 is_test, f"{p}tgt_word_emb")
    for li in range(n_layers):
        dec = decoder_layer(dec, enc, d_model, n_heads, d_inner,
                            dropout_rate, is_test, name=f"{p}dec{li}")
        if ck is not None:
            ck.append(dec)
    logits = layers.fc(dec, tgt_vocab, num_flatten_dims=2,
                       bias_attr=False, param_attr=f"{p}logits.w")
    # fused smoothing: same math as one_hot+label_smooth+soft-label CE
    # but never materializes the [B,T,V] one-hot (HBM-bound at 32k vocab)
    cost = layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(label, [2]),
        label_smooth_eps=label_smooth_eps)
    avg_cost = layers.mean(cost)
    return avg_cost, logits


def build_program(batch_size=None, seq_len=64, d_model=512, n_heads=8,
                  n_layers=6, d_inner=2048, vocab=30000,
                  learning_rate=2.0, warmup_steps=4000,
                  with_optimizer=True, dropout_rate=0.1,
                  recompute=False, name_prefix=""):
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", shape=[seq_len], dtype="int64")
        tgt = layers.data("tgt_ids", shape=[seq_len], dtype="int64")
        label = layers.data("label", shape=[seq_len], dtype="int64")
        ck = [] if recompute else None
        avg_cost, logits = transformer(
            src, tgt, label, src_vocab=vocab, tgt_vocab=vocab,
            max_len=max(seq_len, 256), d_model=d_model, n_heads=n_heads,
            n_layers=n_layers, d_inner=d_inner,
            dropout_rate=dropout_rate, checkpoints=ck,
            name_prefix=name_prefix)
        if with_optimizer:
            lr = layers.learning_rate_scheduler.noam_decay(
                d_model, warmup_steps)
            opt = fluid.optimizer.Adam(
                learning_rate=lr, beta1=0.9, beta2=0.997, epsilon=1e-9)
            if recompute:
                opt = fluid.optimizer.RecomputeOptimizer(opt)
                opt._set_checkpoints(ck)
            opt.minimize(avg_cost)
    return main, startup, avg_cost


# build_beam_decode_program moved to decode_engine (the last decode
# loop folded in — ROADMAP "one decode engine, three fronts"); the
# re-export above keeps every call site and the public signature
# unchanged.
